#!/usr/bin/env python
"""Perf probe: repeated idle measurements of the Ed25519/VRF device paths.

Times each (path, shape) with R repetitions and prints median + min/max —
the measurement discipline VERDICT r3 asked for, in a standalone tool so
kernel work can be steered by medians instead of single-shot noise.

Paths: the r5 split-128 packed-words kernels (the production path) plus
the r4 256-iteration limb/bit-row kernels for regression comparison.
"""
import argparse
import hashlib
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.abspath(__file__)) + "/..")


def timed(fn, reps):
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        vals.append(time.perf_counter() - t0)
    return vals


def report(name, n, vals):
    med = statistics.median(vals)
    spread = (max(vals) - min(vals)) / med if med else 0
    print(f"{name:28s} n={n:5d}  median {n / med:9.1f}/s   "
          f"min {n / max(vals):9.1f}/s  max {n / min(vals):9.1f}/s  "
          f"spread {100 * spread:.0f}%", flush=True)
    return n / med


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--n-ed", type=int, default=4096)
    ap.add_argument("--n-vrf", type=int, default=2048)
    ap.add_argument("--skip-vrf", action="store_true")
    ap.add_argument("--skip-xla", action="store_true")
    ap.add_argument("--old", action="store_true",
                    help="also run the r4 256-iteration kernels")
    args = ap.parse_args()

    import numpy as np

    import jax.numpy as jnp

    from ouroboros_tpu.crypto import ed25519_jax as EJ
    from ouroboros_tpu.crypto import ed25519_ref, vrf_ref
    from ouroboros_tpu.crypto import pallas_kernels as PK
    from ouroboros_tpu.crypto import vrf_jax

    n = args.n_ed
    sk = hashlib.sha256(b"probe").digest()
    vk = ed25519_ref.public_key(sk)
    msgs = [b"m%06d" % i for i in range(n)]
    sigs = [ed25519_ref.sign(sk, m) for m in msgs]

    # --- Ed25519 split/words (production): e2e incl. host prep
    def run_split_e2e():
        (Aw, _sA, Rw, signR, sw, kw), parse_ok = EJ.prepare_words_batch(
            [vk] * n, msgs, sigs)
        xa, xw, yw, known = EJ.GLOBAL_A128_CACHE.assemble([vk] * n)
        ok = np.asarray(PK.ed25519_split_pallas(
            Aw, xa, xw, yw, Rw, signR, sw, kw, n))
        assert ok.sum() == n and known.all(), ok.sum()
    run_split_e2e()   # compile + cache fill
    report("ed split pallas e2e", n, timed(run_split_e2e, args.reps))

    # device-only (inputs pre-staged)
    (Aw, _sA, Rw, signR, sw, kw), _ = EJ.prepare_words_batch(
        [vk] * n, msgs, sigs)
    xa, xw, yw, _known = EJ.GLOBAL_A128_CACHE.assemble([vk] * n)
    dev = [jnp.asarray(a) for a in
           (Aw, xa, xw, yw, Rw, signR.reshape(1, -1), sw, kw)]

    def run_split_dev():
        ok = np.asarray(PK._ed25519_split_jit(*dev, n))
        assert ok.sum() == n
    report("ed split pallas device", n, timed(run_split_dev, args.reps))

    if not args.skip_xla:
        def run_split_xla():
            ok = np.asarray(EJ.verify_full_split_words_kernel(
                dev[0], dev[1], dev[2], dev[3], dev[4], dev[5][0],
                dev[6], dev[7]))
            assert ok.sum() == n
        run_split_xla()
        report("ed split XLA device", n, timed(run_split_xla, args.reps))

    if args.old:
        arrays, _parse_ok = EJ.prepare_bytes_batch([vk] * n, msgs, sigs)
        arrs = [jnp.asarray(a) for a in arrays]
        yA, signA_l, yR, signR_l, s_bits, k_bits = arrs

        def run_old_pallas():
            ok = np.asarray(PK.ed25519_verify_pallas(
                yA, signA_l, yR, signR_l, s_bits, k_bits, n))
            assert ok.sum() == n
        run_old_pallas()
        report("ed r4 pallas device", n, timed(run_old_pallas, args.reps))

    if args.skip_vrf:
        return
    # --- VRF (proof generation is pure-Python EC: cache to disk)
    nv = args.n_vrf
    vsk = hashlib.sha256(b"probe-vrf").digest()
    vvk = vrf_ref.public_key(vsk)
    alphas = [b"a%d" % i for i in range(nv)]
    cache = os.path.join(tempfile.gettempdir(),
                         f"ouro-probe-proofs-{nv}.bin")
    if os.path.exists(cache):
        raw = open(cache, "rb").read()
        proofs = [raw[i * 80:(i + 1) * 80] for i in range(nv)]
    else:
        proofs = [vrf_ref.prove(vsk, a) for a in alphas]
        open(cache, "wb").write(b"".join(proofs))

    if not args.skip_xla:
        def run_vrf_xla():
            st = vrf_jax._submit([vvk] * nv, alphas, proofs, nv,
                                 runner=None)
            oks, _ = vrf_jax._finish(*st, nv)
            assert all(oks)
        run_vrf_xla()
        report("vrf words XLA e2e", nv, timed(run_vrf_xla, args.reps))

    def run_vrf_pallas():
        st = vrf_jax._submit([vvk] * nv, alphas, proofs, nv,
                             runner=PK.vrf_verify_pallas)
        oks, _ = vrf_jax._finish(*st, nv)
        assert all(oks)
    run_vrf_pallas()
    report("vrf words pallas e2e", nv, timed(run_vrf_pallas, args.reps))

    # betas
    def run_betas():
        st, decode_ok = vrf_jax._submit_betas(proofs, nv,
                                              runner=PK.gamma8_pallas)
        bs = vrf_jax._finish_betas(np.asarray(st), decode_ok, nv)
        assert all(b is not None for b in bs)
    run_betas()
    report("beta words pallas e2e", nv, timed(run_betas, args.reps))


if __name__ == "__main__":
    main()
