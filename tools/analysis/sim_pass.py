"""Pass 3 — sim-determinism lint.

Everything under ouroboros_tpu/ is written against the simharness facade
and must stay replayable on the deterministic Sim scheduler; only
simharness/io_runtime.py and network/socket_bearer.py are the declared
real-IO boundary.  This pass walks every *async* function outside that
boundary (nested helper defs included — they run on the same cooperative
scheduler unless explicitly shipped to an executor) and flags operations
that would block the event loop or smuggle in wall-clock/OS entropy:

- SIM001 real-sleep: time.sleep() stalls the whole cooperative scheduler
  and reads the real clock; use sim.sleep.
- SIM002 global-rng: module-global random.*() draws from interpreter-wide
  state, so interleaving changes results between runs; use a seeded
  random.Random instance plumbed from the test/sim config (constructing
  random.Random(seed)/SystemRandom is allowed).
- SIM003 real-threads: threading.* bypasses the cooperative scheduler
  entirely; use sim.spawn.
- SIM004 raw-socket: socket.*() calls are real network IO; use the
  snocket/bearer abstractions (socket module *constants* are fine).
- SIM005 blocking-file-io: open()/io.open()/os.open() block the loop; go
  through storage.fs or the IO runtime's executor.

One rule is scoped to ouroboros_tpu/node/ only (the peer-facing layer
whose liveness the protocol watchdogs own):

- SIM006 unbounded-receive: an `await` on a channel/queue receive
  (`.recv()` / `.collect()` / `sim.atomically(...queue.get...)`) with no
  time limit parks the thread forever when the peer goes silent — route
  peer-facing receives through node/watchdog.py's recv_with_limit/
  collect_with_limit (per-state ProtocolTimeLimits) or wrap the await in
  sim.timeout.  Receives that legitimately wait forever (server loops on
  client agency, internal work queues) are baselined with justifications.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from . import Finding, register, relpath
from .astutil import dotted_name, iter_py_files, parse_file

SCAN_DIRS = ("ouroboros_tpu",)
IO_BOUNDARY = (
    "ouroboros_tpu/simharness/io_runtime.py",
    "ouroboros_tpu/network/socket_bearer.py",
)
# SIM006 applies only under this prefix (repo-relative, forward slashes)
NODE_PREFIX = "ouroboros_tpu/node/"

_RNG_FACTORIES = {"Random", "SystemRandom"}
_OPEN_CALLS = {"open", "io.open", "os.open"}
# receive method names whose bare await in node/ code is unbounded
_RECV_ATTRS = {"recv", "collect"}


def _is_unbounded_receive(call: ast.Call) -> bool:
    """A channel/queue receive with no built-in bound: session.recv(),
    session.collect(), or sim.atomically(<something reading a queue's
    .get>).  The watchdog helpers (recv_with_limit/collect_with_limit)
    and sim.timeout(...) wrappers do not match."""
    name = dotted_name(call.func)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _RECV_ATTRS:
        return True
    if leaf == "atomically":
        for arg in call.args:
            # sim.atomically(q.get) or sim.atomically(lambda tx: q.get(tx))
            if isinstance(arg, ast.Attribute) and arg.attr == "get":
                return True
            if isinstance(arg, ast.Lambda):
                for sub in ast.walk(arg.body):
                    if isinstance(sub, ast.Call):
                        sub_name = dotted_name(sub.func) or ""
                        if sub_name.rsplit(".", 1)[-1] == "get":
                            return True
    return False


class _AsyncBodyLint(ast.NodeVisitor):
    def __init__(self, file: str):
        self.file = file
        self.findings: List[Finding] = []
        self._stack: List[str] = []
        self._async_depth = 0
        self._node_scope = file.replace("\\", "/").startswith(NODE_PREFIX)

    @property
    def qualname(self) -> str:
        return ".".join(self._stack) or "<module>"

    def _visit_scope(self, node, is_async: bool):
        self._stack.append(node.name)
        self._async_depth += is_async
        try:
            self.generic_visit(node)
        finally:
            self._async_depth -= is_async
            self._stack.pop()

    def visit_FunctionDef(self, node):
        self._visit_scope(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_scope(node, is_async=True)

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._stack.pop()

    def _add(self, node, rule, message):
        self.findings.append(Finding(
            file=self.file, line=node.lineno, rule=rule,
            symbol=self.qualname, message=message))

    def visit_Call(self, node: ast.Call):
        if self._async_depth > 0:
            name = dotted_name(node.func)
            if name == "time.sleep":
                self._add(node, "SIM001",
                          "time.sleep blocks the cooperative scheduler and "
                          "reads the real clock; use sim.sleep")
            elif name and name.startswith("random.") and \
                    name.split(".", 1)[1] not in _RNG_FACTORIES:
                self._add(node, "SIM002",
                          f"{name}() uses interpreter-global RNG state; "
                          f"use a seeded random.Random instance")
            elif name and name.startswith("threading."):
                self._add(node, "SIM003",
                          f"{name}() spawns a real thread outside the "
                          f"Sim scheduler; use sim.spawn")
            elif name and name.startswith("socket."):
                self._add(node, "SIM004",
                          f"{name}() is real network IO outside the "
                          f"declared boundary; use snocket/bearer")
            elif name in _OPEN_CALLS:
                self._add(node, "SIM005",
                          f"{name}() is blocking file IO on the "
                          f"cooperative scheduler; use storage.fs or the "
                          f"IO runtime executor")
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await):
        if self._async_depth > 0 and self._node_scope \
                and isinstance(node.value, ast.Call) \
                and _is_unbounded_receive(node.value):
            name = dotted_name(node.value.func)
            self._add(node, "SIM006",
                      f"unbounded await on {name}() — a silent peer parks "
                      f"this thread forever; use node/watchdog.py's "
                      f"recv_with_limit/collect_with_limit or sim.timeout")
        self.generic_visit(node)


def lint_source(source: str, file: str) -> List[Finding]:
    """Run the sim pass over one source text (fixture entry point)."""
    lint = _AsyncBodyLint(file)
    lint.visit(ast.parse(source, filename=file))
    return lint.findings


def run_files(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        lint = _AsyncBodyLint(relpath(path))
        lint.visit(parse_file(path))
        findings.extend(lint.findings)
    return findings


@register("sim")
def run() -> List[Finding]:
    return run_files(iter_py_files(*SCAN_DIRS, exclude=IO_BOUNDARY))
