"""Pass 5 — observability hot-path lint (OBS001).

A nop `Tracer` makes the trace() CALL free, but Python still evaluates
the call's ARGUMENT first: a dataclass event build or an f-string
formatted for a tracer that is not listening is pure hot-path waste —
exactly the cost the contra-tracer design exists to avoid.  On the
replay hot paths (crypto/, parallel/) every tracer call site whose
payload does work must therefore sit under a `tracer.active` guard:

    if tracer.active:
        tracer.trace(WindowDispatched(ne, nv, f"{key}"))   # ok
    tracer.trace(WindowDispatched(ne, nv))                 # OBS001
    tracer.trace(EVENT_CONSTANT)                           # ok (cheap)

- OBS001 unguarded-event-construction: `X.trace(arg)` / `X.trace(...)`
  via an attribute chain ending in `.trace`, or a bare/dotted
  `trace_event(...)` call, whose argument expression contains a Call,
  an f-string (JoinedStr), a `%`/`+` on strings or a comprehension —
  and no enclosing `if` whose test mentions `.active`.

Cheap payloads (names, constants, attribute reads, plain tuples of
those) pass: a tuple build of locals is two bytecode ops, the guard
would cost as much as it saves.  Cold-path sites (an autotune
measurement that runs once per shape per process) are tolerated via
justified baseline entries, the same contract as every other pass.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from . import Finding, register, relpath
from .astutil import QualnameVisitor, dotted_name, iter_py_files, parse_file

SCAN_DIRS = ("ouroboros_tpu/crypto", "ouroboros_tpu/parallel")

_TRACE_FN_NAMES = {"trace_event", "sim.trace_event"}


def _is_trace_call(node: ast.Call) -> bool:
    if isinstance(node.func, ast.Attribute) and node.func.attr == "trace":
        return True
    name = dotted_name(node.func)
    return name in _TRACE_FN_NAMES or (
        name is not None and name.endswith(".trace_event"))


def _expensive(node: ast.AST) -> bool:
    """Does evaluating this argument expression do real work?"""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Call, ast.JoinedStr, ast.ListComp,
                            ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return True
        if isinstance(sub, ast.BinOp):
            # string build via % or + on anything non-constant-foldable
            if isinstance(sub.op, (ast.Mod, ast.Add)) and not (
                    isinstance(sub.left, ast.Constant)
                    and isinstance(sub.right, ast.Constant)):
                return True
    return False


def _guard_mentions_active(test: ast.AST) -> bool:
    return any(isinstance(sub, ast.Attribute) and sub.attr == "active"
               for sub in ast.walk(test))


class _ObsLint(QualnameVisitor):
    def __init__(self, file: str, findings: List[Finding]):
        super().__init__()
        self.file = file
        self.findings = findings
        self._guard_depth = 0

    def visit_If(self, node: ast.If):
        guarded = _guard_mentions_active(node.test)
        self._guard_depth += guarded
        for child in node.body:
            self.visit(child)
        self._guard_depth -= guarded
        for child in node.orelse:
            self.visit(child)

    def visit_IfExp(self, node: ast.IfExp):
        guarded = _guard_mentions_active(node.test)
        self.visit(node.test)
        self._guard_depth += guarded
        self.visit(node.body)
        self._guard_depth -= guarded
        self.visit(node.orelse)

    def visit_Call(self, node: ast.Call):
        if _is_trace_call(node) and self._guard_depth == 0:
            payload = list(node.args) + [kw.value for kw in node.keywords]
            if any(_expensive(a) for a in payload):
                self.findings.append(Finding(
                    file=self.file, line=node.lineno, rule="OBS001",
                    symbol=self.qualname,
                    message="event constructed (call/f-string) for a "
                            "tracer that may be nop; guard the call "
                            "site with `if tracer.active:` on hot "
                            "paths"))
        self.generic_visit(node)


def lint_source(source: str, file: str) -> List[Finding]:
    """Run the OBS pass over one source text (fixture entry point)."""
    findings: List[Finding] = []
    _ObsLint(file, findings).visit(ast.parse(source, filename=file))
    return sorted(set(findings))


def run_files(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        lint = _ObsLint(relpath(path), findings)
        lint.visit(parse_file(path))
    return sorted(set(findings))


@register("obs")
def run() -> List[Finding]:
    return run_files(iter_py_files(*SCAN_DIRS))
