"""Pass 5 — observability hot-path lint (OBS001, OBS002).

A nop `Tracer` makes the trace() CALL free, but Python still evaluates
the call's ARGUMENT first: a dataclass event build or an f-string
formatted for a tracer that is not listening is pure hot-path waste —
exactly the cost the contra-tracer design exists to avoid.  On the
replay hot paths (crypto/, parallel/) every tracer call site whose
payload does work must therefore sit under a `tracer.active` guard:

    if tracer.active:
        tracer.trace(WindowDispatched(ne, nv, f"{key}"))   # ok
    tracer.trace(WindowDispatched(ne, nv))                 # OBS001
    tracer.trace(EVENT_CONSTANT)                           # ok (cheap)

- OBS001 unguarded-event-construction: `X.trace(arg)` / `X.trace(...)`
  via an attribute chain ending in `.trace`, or a bare/dotted
  `trace_event(...)` call, whose argument expression contains a Call,
  an f-string (JoinedStr), a `%`/`+` on strings or a comprehension —
  and no enclosing `if` whose test mentions `.active`.

- OBS002 unbound-instrument-observation (ISSUE 9): a histogram write
  through a FRESH registry lookup — `histogram("name").observe(v)` /
  `reg.histogram(...).observe(v)` (and the counter/gauge analogues
  `.inc(...)`/`.set(...)` chained onto a `counter(`/`gauge(` lookup).
  Registry creation is an idempotent dict probe plus a kind check —
  dozens of bytecode ops repeated per observation on paths that run
  per window or per tx.  Bind the handle ONCE at module/init scope:

    _LAT = _metrics.latency_histogram("pipeline.submit_drain_secs")
    ...
    _LAT.observe(dt)                                       # ok
    _metrics.histogram("pipeline...").observe(dt)          # OBS002

  OBS002 scans the whole ouroboros_tpu package (any module may grow a
  hot loop); genuinely cold sites — a once-per-scrape handler — are
  tolerated via justified baseline entries.

- OBS003 dynamic-instrument-name (ISSUE 14): a metric name BUILT from
  runtime values — an f-string, `%`/`+` string concat, `.format(...)`
  or `str(...)` as the name argument of a registry factory
  (counter/gauge/histogram/latency_histogram).  A series per raw
  runtime value (peer addr, protocol number) is an unbounded-
  cardinality bomb on an O(100)-node net; route the dynamic part
  through the bounded-label helper instead:

    _net.labeled_counter("watchdog.firings_by_protocol",
                         protocol=proto)                   # ok
    _metrics.counter(f"watchdog.firings.{proto}")          # OBS003

  OBS003 scans the whole package; observe/netmetrics.py itself (the
  helper's implementation) is exempt.  Names bounded by construction
  (a small author-declared vocabulary, memoised per handle) are
  tolerated via justified baseline entries.

Cheap payloads (names, constants, attribute reads, plain tuples of
those) pass OBS001: a tuple build of locals is two bytecode ops, the
guard would cost as much as it saves.  Cold-path sites (an autotune
measurement that runs once per shape per process) are tolerated via
justified baseline entries, the same contract as every other pass.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from . import Finding, register, relpath
from .astutil import QualnameVisitor, dotted_name, iter_py_files, parse_file

SCAN_DIRS = ("ouroboros_tpu/crypto", "ouroboros_tpu/parallel")
# OBS002/OBS003 apply package-wide: pre-binding and bounded labels cost
# nothing, and hot loops appear outside crypto/ (pipeline drains,
# mempool admission, mux)
OBS2_SCAN_DIRS = ("ouroboros_tpu",)
# the bounded-label helper builds labeled names BY DESIGN — exempt from
# its own rule
OBS3_EXEMPT_FILES = ("ouroboros_tpu/observe/netmetrics.py",)

_TRACE_FN_NAMES = {"trace_event", "sim.trace_event"}

# instrument-factory name suffix -> the write method whose chaining we
# flag (quantile/snapshot reads on a fresh lookup are cold by nature)
_INSTRUMENT_WRITES = {"histogram": "observe",
                      "latency_histogram": "observe",
                      "counter": "inc",
                      "gauge": "set"}

# factory leafs whose NAME argument OBS003 inspects
_INSTRUMENT_FACTORIES = frozenset(_INSTRUMENT_WRITES)


def _is_trace_call(node: ast.Call) -> bool:
    if isinstance(node.func, ast.Attribute) and node.func.attr == "trace":
        return True
    name = dotted_name(node.func)
    return name in _TRACE_FN_NAMES or (
        name is not None and name.endswith(".trace_event"))


def _expensive(node: ast.AST) -> bool:
    """Does evaluating this argument expression do real work?"""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Call, ast.JoinedStr, ast.ListComp,
                            ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return True
        if isinstance(sub, ast.BinOp):
            # string build via % or + on anything non-constant-foldable
            if isinstance(sub.op, (ast.Mod, ast.Add)) and not (
                    isinstance(sub.left, ast.Constant)
                    and isinstance(sub.right, ast.Constant)):
                return True
    return False


def _guard_mentions_active(test: ast.AST) -> bool:
    return any(isinstance(sub, ast.Attribute) and sub.attr == "active"
               for sub in ast.walk(test))


def _dynamic_name_arg(node: ast.Call) -> bool:
    """Is this call's metric-name argument BUILT from runtime values —
    an f-string, a non-constant `%`/`+` concat, `.format(...)` or
    `str(...)`?  Plain names/attributes are not flagged (the rule
    targets construction at the call site, where the helper belongs)."""
    arg = None
    if node.args:
        arg = node.args[0]
    else:
        for kw in node.keywords:
            if kw.arg == "name":
                arg = kw.value
                break
    if arg is None:
        return False
    if isinstance(arg, ast.JoinedStr):
        return True
    if isinstance(arg, ast.BinOp) and isinstance(arg.op,
                                                 (ast.Mod, ast.Add)):
        return not (isinstance(arg.left, ast.Constant)
                    and isinstance(arg.right, ast.Constant))
    if isinstance(arg, ast.Call):
        if isinstance(arg.func, ast.Attribute) \
                and arg.func.attr == "format":
            return True
        return dotted_name(arg.func) == "str"
    return False


def _instrument_factory_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name is None:
        return False
    return name.rsplit(".", 1)[-1] in _INSTRUMENT_FACTORIES


def _unbound_instrument_write(node: ast.Call) -> bool:
    """Is `node` a metric write chained directly onto an instrument
    FACTORY call — `<...>.histogram("x").observe(v)` and friends?"""
    if not isinstance(node.func, ast.Attribute):
        return False
    recv = node.func.value
    if not isinstance(recv, ast.Call):
        return False
    factory = dotted_name(recv.func)
    if factory is None:
        return False
    leaf = factory.rsplit(".", 1)[-1]
    return _INSTRUMENT_WRITES.get(leaf) == node.func.attr


class _ObsLint(QualnameVisitor):
    def __init__(self, file: str, findings: List[Finding],
                 rules: Iterable[str] = ("OBS001", "OBS002")):
        super().__init__()
        self.file = file
        self.findings = findings
        self.rules = frozenset(rules)
        self._guard_depth = 0

    def visit_If(self, node: ast.If):
        guarded = _guard_mentions_active(node.test)
        self._guard_depth += guarded
        for child in node.body:
            self.visit(child)
        self._guard_depth -= guarded
        for child in node.orelse:
            self.visit(child)

    def visit_IfExp(self, node: ast.IfExp):
        guarded = _guard_mentions_active(node.test)
        self.visit(node.test)
        self._guard_depth += guarded
        self.visit(node.body)
        self._guard_depth -= guarded
        self.visit(node.orelse)

    def visit_Call(self, node: ast.Call):
        if "OBS001" in self.rules and _is_trace_call(node) \
                and self._guard_depth == 0:
            payload = list(node.args) + [kw.value for kw in node.keywords]
            if any(_expensive(a) for a in payload):
                self.findings.append(Finding(
                    file=self.file, line=node.lineno, rule="OBS001",
                    symbol=self.qualname,
                    message="event constructed (call/f-string) for a "
                            "tracer that may be nop; guard the call "
                            "site with `if tracer.active:` on hot "
                            "paths"))
        if "OBS002" in self.rules and _unbound_instrument_write(node):
            self.findings.append(Finding(
                file=self.file, line=node.lineno, rule="OBS002",
                symbol=self.qualname,
                message="instrument write through a fresh registry "
                        "lookup; pre-bind the handle once "
                        "(H = metrics.histogram(...)) at module/init "
                        "scope and call H.observe(v) on the hot path"))
        if "OBS003" in self.rules and _instrument_factory_call(node) \
                and _dynamic_name_arg(node):
            self.findings.append(Finding(
                file=self.file, line=node.lineno, rule="OBS003",
                symbol=self.qualname,
                message="metric name built from runtime values "
                        "(unbounded registry cardinality); route the "
                        "dynamic part through the bounded-label helper "
                        "(observe/netmetrics.py labeled_counter/"
                        "labeled_gauge/peer_label)"))
        self.generic_visit(node)


def lint_source(source: str, file: str,
                rules: Iterable[str] = ("OBS001", "OBS002", "OBS003")
                ) -> List[Finding]:
    """Run the OBS pass over one source text (fixture entry point)."""
    findings: List[Finding] = []
    _ObsLint(file, findings, rules).visit(
        ast.parse(source, filename=file))
    return sorted(set(findings))


def run_files(paths: Iterable[str],
              rules: Iterable[str] = ("OBS001", "OBS002", "OBS003")
              ) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        rel = relpath(path)
        file_rules = rules if rel not in OBS3_EXEMPT_FILES else \
            tuple(r for r in rules if r != "OBS003")
        lint = _ObsLint(rel, findings, file_rules)
        lint.visit(parse_file(path))
    return sorted(set(findings))


@register("obs")
def run() -> List[Finding]:
    # OBS001+OBS002+OBS003 on the crypto/parallel hot paths; OBS002+
    # OBS003 over the rest of the package (OBS001's tracer-payload rule
    # would drown in the cold protocol layers, where a guard costs more
    # than it saves — the unbound-handle and bounded-label rules are
    # cheap to satisfy anywhere)
    hot = set(iter_py_files(*SCAN_DIRS))
    findings = run_files(sorted(hot))
    rest = [p for p in iter_py_files(*OBS2_SCAN_DIRS) if p not in hot]
    findings += run_files(sorted(rest), rules=("OBS002", "OBS003"))
    return sorted(set(findings))
