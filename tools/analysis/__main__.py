"""ouro-lint CLI.

    python -m tools.analysis [--strict] [--passes protocol,jax,sim,conc,obs]
                             [--baseline PATH | --no-baseline]
                             [--write-baseline]
                             [--format text|json|sarif]

Exit codes: 0 clean, 1 non-baselined findings (under --strict also stale
baseline entries), 2 internal error — identical across output formats,
so CI keys off the exit code and feeds the JSON/SARIF to annotations.
Baselined findings are printed but never block.  Runs fully on CPU: the
passes are AST walks plus one import of the (jax-free) protocols
package, so JAX_PLATFORMS=cpu is forced before anything else can pull
jax in.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# `python -m tools.analysis` from anywhere: make the repo root importable
# for the protocols import walk.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import argparse  # noqa: E402

from tools.analysis import (  # noqa: E402
    BASELINE_PATH, Baseline, run_passes,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="ouro-lint: protocol-soundness, JAX-hot-path and "
                    "sim-determinism static analysis")
    ap.add_argument("--strict", action="store_true",
                    help="also fail (exit 1) on stale baseline entries")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset of: protocol,jax,sim,conc,obs")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"),
                    help="output format (default text; json/sarif print "
                         "one document on stdout for CI/editors)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help=f"baseline file (default {BASELINE_PATH})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding blocks")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(then edit in the justifications)")
    args = ap.parse_args(argv)

    names = args.passes.split(",") if args.passes else None
    if args.write_baseline and not os.path.exists(args.baseline):
        on_disk = Baseline()               # creating a fresh baseline file
    else:
        on_disk = Baseline.load(args.baseline)  # typo'd path -> exit 2
    report = run_passes(names, Baseline() if args.no_baseline else on_disk)

    if args.write_baseline:
        regenerated = Baseline.from_findings(report.by_pass,
                                             existing=on_disk)
        regenerated.dump(args.baseline)
        print(f"wrote {sum(len(v) for v in regenerated.entries.values())} "
              f"entries to {args.baseline}")
        return 0

    if args.format != "text":
        import json as _json

        from tools.analysis.render import report_to_json, report_to_sarif
        doc = report_to_sarif(report) if args.format == "sarif" \
            else report_to_json(report, strict=args.strict)
        print(_json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in report.baselined:
            print(f"baselined: {f.render()}")
        for f in report.new:
            print(f.render())
        for pass_name, key in report.stale:
            print(f"stale baseline entry [{pass_name}]: {key[0]} {key[1]} "
                  f"[{key[2]}] — finding no longer exists; remove it")

        checked = ", ".join(f"{name}: {len(fs)} finding(s)"
                            for name, fs in sorted(report.by_pass.items()))
        print(f"ouro-lint: {checked}; {len(report.new)} blocking, "
              f"{len(report.baselined)} baselined, {len(report.stale)} stale")

    if report.new:
        return 1
    if args.strict and report.stale:
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as e:                      # internal error -> 2
        print(f"ouro-lint internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
