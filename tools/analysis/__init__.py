"""ouro-lint — static analysis for the ouroboros_tpu rebuild.

The Haskell reference gets mini-protocol conformance at compile time
(typed-protocols GADTs); the Python rebuild moved those guarantees to
runtime (network/typed.py).  This package restores a compile-time-shaped
safety net as three registry/AST-driven passes:

- protocol  (protocol_pass.py): ProtocolSpec soundness — agency totality,
  transition well-formedness, reachability, codec coverage.
- jax       (jax_pass.py): host-sync / retrace hazards inside jitted call
  graphs under crypto/ and parallel/.
- sim       (sim_pass.py): real-clock / real-IO / nondeterminism leaks in
  async code that runs on the deterministic Sim scheduler.
- conc      (conc_pass.py): STM concurrency idioms that create races.
- obs       (obs_pass.py): unguarded event construction at Tracer call
  sites on the crypto/parallel hot paths.

Findings are structured (file, line, rule, symbol, message).  A committed
`baseline.json` suppresses known pre-existing findings by
(file, rule, symbol) — line-independent, so unrelated edits don't churn
the baseline.  Run `python -m tools.analysis --strict` (exit 0 clean,
1 findings, 2 internal error); see README.md for the rule catalog.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


@dataclass(frozen=True, order=True)
class Finding:
    """One lint result.  `symbol` is the enclosing def/class qualname (AST
    passes) or the spec/attr name (protocol pass) — the stable identity the
    baseline matches on, so findings survive line drift."""
    file: str       # repo-relative, forward slashes
    line: int
    rule: str       # e.g. "PROTO001"
    symbol: str
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.file, self.rule, self.symbol)

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.symbol}] " \
               f"{self.message}"


def relpath(path: str) -> str:
    return os.path.relpath(os.path.abspath(path), REPO_ROOT).replace(
        os.sep, "/")


# --- pass registry ----------------------------------------------------------

PASSES: Dict[str, Callable[[], List[Finding]]] = {}


def register(name: str):
    def deco(fn):
        PASSES[name] = fn
        return fn
    return deco


def _ensure_passes_loaded() -> None:
    from . import (  # noqa: F401
        conc_pass, jax_pass, obs_pass, protocol_pass, sim_pass,
    )


# --- baseline ---------------------------------------------------------------

@dataclass
class Baseline:
    """Per-pass suppression sets.  Each entry carries a justification so the
    reason a finding is tolerated is reviewable next to the suppression."""
    entries: Dict[str, List[dict]] = field(default_factory=dict)

    def keys_for(self, pass_name: str) -> Dict[Tuple[str, str, str], str]:
        out = {}
        for e in self.entries.get(pass_name, []):
            out[(e["file"], e["rule"], e["symbol"])] = e.get(
                "justification", "")
        return out

    @classmethod
    def load(cls, path: str = BASELINE_PATH) -> "Baseline":
        if not os.path.exists(path):
            if os.path.abspath(path) != os.path.abspath(BASELINE_PATH):
                # a typo'd --baseline path must not silently drop every
                # committed suppression; only the default may be absent
                raise FileNotFoundError(f"baseline file not found: {path}")
            return cls()
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError(f"{path}: baseline must be a JSON object")
        for name, items in data.items():
            for e in items:
                for k in ("file", "rule", "symbol", "justification"):
                    if k not in e:
                        raise ValueError(
                            f"{path}: baseline entry in {name!r} missing "
                            f"{k!r}: {e}")
        return cls(entries=data)

    @classmethod
    def from_findings(cls, by_pass: Dict[str, List[Finding]],
                      existing: Optional["Baseline"] = None) -> "Baseline":
        """Baseline regenerated from current findings.  Sections for passes
        not in `by_pass` and justifications for keys that persist are
        carried over from `existing` — a rewrite never silently drops
        hand-written suppressions for passes that didn't run."""
        existing = existing or cls()
        entries = dict(existing.entries)
        for name, fs in sorted(by_pass.items()):
            kept = existing.keys_for(name)
            # dedup by the baseline's own identity (file, rule, symbol):
            # two findings in one symbol (e.g. a set_notify + its _value
            # fallback) must yield ONE entry, or edits leave a
            # contradictory twin the matcher can never distinguish
            seen: set = set()
            entries[name] = []
            for f in sorted(set(fs)):
                if f.key in seen:
                    continue
                seen.add(f.key)
                entries[name].append(
                    {"file": f.file, "rule": f.rule, "symbol": f.symbol,
                     "justification": kept.get(f.key)
                     or "TODO: justify or fix"})
        return cls(entries=entries)

    def dump(self, path: str = BASELINE_PATH) -> None:
        """Canonical form: sections alphabetical, entries sorted by
        (file, rule, symbol), entry keys in (file, rule, symbol,
        justification) order.  load->dump round-trips byte-identically,
        so a --write-baseline on an unchanged tree produces a zero-line
        diff (tests/test_static_analysis.py gates this)."""
        data = {
            name: [{"file": e["file"], "rule": e["rule"],
                    "symbol": e["symbol"],
                    "justification": e.get("justification", "")}
                   for e in sorted(self.entries[name],
                                   key=lambda e: (e["file"], e["rule"],
                                                  e["symbol"]))]
            for name in sorted(self.entries)
        }
        with open(path, "w") as f:
            json.dump(data, f, indent=2)
            f.write("\n")


@dataclass
class Report:
    """Outcome of a full run: findings split by baseline status."""
    by_pass: Dict[str, List[Finding]]
    new: List[Finding]            # not in the baseline — blocking
    baselined: List[Finding]      # suppressed, still visible
    stale: List[Tuple[str, Tuple[str, str, str]]]  # baseline w/o finding


def run_passes(names: Optional[List[str]] = None,
               baseline: Optional[Baseline] = None) -> Report:
    _ensure_passes_loaded()
    names = names or sorted(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es): {unknown}; "
                         f"have {sorted(PASSES)}")
    baseline = baseline if baseline is not None else Baseline()
    by_pass: Dict[str, List[Finding]] = {}
    new: List[Finding] = []
    old: List[Finding] = []
    stale: List[Tuple[str, Tuple[str, str, str]]] = []
    for name in names:
        findings = sorted(PASSES[name]())
        by_pass[name] = findings
        suppressed = baseline.keys_for(name)
        seen = set()
        for f in findings:
            if f.key in suppressed:
                old.append(f)
                seen.add(f.key)
            else:
                new.append(f)
        for key in suppressed:
            if key not in seen:
                stale.append((name, key))
    return Report(by_pass=by_pass, new=new, baselined=old, stale=stale)
