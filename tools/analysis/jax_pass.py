"""Pass 2 — JAX hot-path lint for crypto/ and parallel/.

Walks the AST of every module under ouroboros_tpu/crypto and
ouroboros_tpu/parallel, computes the set of *traced* functions (jitted
directly, passed to jax.jit / lax control-flow / vmap / shard_map, or
reachable from one through same-module calls), and flags host-sync and
retrace hazards inside those bodies:

- JAX001 host-conversion: int()/float()/bool() applied to a non-static
  expression inside a traced body — forces a device sync (or a tracer
  error) at run time.
- JAX002 item-sync: `.item()` inside a traced body — a blocking
  device->host transfer per element.
- JAX003 numpy-in-jit: `np.*` / `numpy.*` call inside a traced body —
  either a silent trace-time constant or a tracer TypeError; hot paths
  must use jnp/lax.
- JAX004 jit-per-call: `jax.jit(...)` constructed inside a function body
  that is not memoised (functools.lru_cache/functools.cache) — a fresh
  jit wrapper (and XLA compile) every invocation.
- JAX005 lambda-to-jit: a known-jitted callable invoked with an inline
  lambda argument — a fresh function object per call, so the jit cache
  can never hit (and a tracer error unless marked static).
- JAX006 jit-in-loop: jax.jit / shard_map / pallas_call CONSTRUCTED
  lexically inside a for/while loop — a per-window or per-rep kernel
  rebuild, the retrace hazard behind BENCH_r05's mid-bench retunes.
  Memoised builders called from loops are fine; building the wrapper in
  the loop body never is.

The traced-set computation is deliberately same-module only: cross-module
calls (e.g. field_jax helpers) are linted in their own module when they
are jitted/traced there, which keeps the pass O(files) with no import cost.
The scan covers crypto/, parallel/ and the top-level bench.py (the
per-rep loops the JAX006 hazard lives in).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from . import Finding, register, relpath
from .astutil import QualnameVisitor, dotted_name, iter_py_files, parse_file

SCAN_DIRS = ("ouroboros_tpu/crypto", "ouroboros_tpu/parallel", "bench.py")

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
# Calls whose function-valued arguments are traced when invoked.
_TRACING_CALLS = {
    "jax.jit", "jit", "jax.pjit", "pjit",
    "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.shard_map", "shard_map",
    "lax.fori_loop", "jax.lax.fori_loop",
    "lax.scan", "jax.lax.scan",
    "lax.while_loop", "jax.lax.while_loop",
    "lax.cond", "jax.lax.cond",
    "lax.switch", "jax.lax.switch",
    "lax.map", "jax.lax.map",
    "lax.associative_scan", "jax.lax.associative_scan",
}
_CACHE_DECORATORS = {"functools.lru_cache", "lru_cache",
                     "functools.cache", "cache"}
# kernel-wrapper constructions JAX006 watches inside loop bodies
_KERNEL_BUILDERS = _JIT_NAMES | {
    "jax.shard_map", "shard_map",
    "pl.pallas_call", "pltpu.pallas_call", "pallas_call",
}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def _is_static_expr(node: ast.AST) -> bool:
    """Expressions whose int()/bool() conversion is trace-safe: literals,
    len(), and shape/dtype metadata (plus arithmetic over those)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call):
        name = _call_name(node)
        if name == "len":
            return True
        return False
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return True
        return False
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    return False


def _decorator_jits(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES:
            return True            # @jax.jit(static_argnums=...)
        if fname in ("functools.partial", "partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _decorator_caches(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _CACHE_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        return dotted_name(dec.func) in _CACHE_DECORATORS
    return False


class _ModuleIndex(ast.NodeVisitor):
    """First sweep: function defs by bare name, traced roots, call graph."""

    def __init__(self):
        self.defs: Dict[str, List[ast.AST]] = {}
        self.roots: Set[str] = set()       # bare names of traced functions
        self.traced_lambdas: List[ast.Lambda] = []
        self.calls: Dict[str, Set[str]] = {}   # caller bare name -> callees
        self.jitted_names: Set[str] = set()    # names wrapped by jax.jit
        self.enclosing: Dict[int, tuple] = {}  # def node id -> outer defs
        self._stack: List[str] = []

    def _visit_def(self, node):
        self.defs.setdefault(node.name, []).append(node)
        self.enclosing[id(node)] = tuple(self._stack)
        if any(_decorator_jits(d) for d in node.decorator_list):
            self.roots.add(node.name)
            self.jitted_names.add(node.name)   # the def IS the jit wrapper
        self._stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if self._stack:
            caller = self._stack[-1]
            if isinstance(node.func, ast.Name):
                self.calls.setdefault(caller, set()).add(node.func.id)
        if name in _TRACING_CALLS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self.roots.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    self.traced_lambdas.append(arg)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        # `fast = jax.jit(f)`: calls to `fast` hit the jit cache, so THAT
        # is the name JAX005 watches (not the raw `f`, which stays a
        # plain Python callable).
        if isinstance(node.value, ast.Call) and \
                _call_name(node.value) in _JIT_NAMES:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.jitted_names.add(t.id)
        self.generic_visit(node)

    def traced_set(self) -> Set[str]:
        """Closure of traced roots over the same-module call graph."""
        traced = set(self.roots)
        frontier = list(traced)
        while frontier:
            fn = frontier.pop()
            for callee in self.calls.get(fn, ()):
                if callee in self.defs and callee not in traced:
                    traced.add(callee)
                    frontier.append(callee)
        return traced


class _TracedBodyLint(QualnameVisitor):
    """Flags JAX001/002/003 within one traced function subtree."""

    def __init__(self, file: str, findings: List[Finding], prefix: str):
        super().__init__()
        self.file = file
        self.findings = findings
        self._prefix = prefix

    def _add(self, node, rule, message):
        qn = self.qualname
        if qn == "<module>" or qn == self._prefix:
            symbol = self._prefix
        elif qn.startswith(self._prefix + "."):
            symbol = qn
        else:
            symbol = f"{self._prefix}.{qn}"
        self.findings.append(Finding(
            file=self.file, line=node.lineno, rule=rule,
            symbol=symbol, message=message))

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if name in ("int", "float", "bool") and node.args and \
                not _is_static_expr(node.args[0]):
            self._add(node, "JAX001",
                      f"{name}() on a traced value forces a host sync "
                      f"inside a jitted body")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args:
            self._add(node, "JAX002",
                      ".item() inside a jitted body is a per-element "
                      "device->host transfer")
        elif name and (name.startswith("np.") or name.startswith("numpy.")):
            self._add(node, "JAX003",
                      f"{name}() inside a jitted body runs on host at "
                      f"trace time; use jnp/lax")
        self.generic_visit(node)


class _JitPerCallLint(QualnameVisitor):
    """Flags JAX004 (jit built per call), JAX005 (lambda into a jitted
    callable) and JAX006 (kernel wrapper built inside a loop) over the
    whole module."""

    def __init__(self, file: str, findings: List[Finding],
                 jitted_names: Set[str]):
        super().__init__()
        self.file = file
        self.findings = findings
        self.jitted_names = jitted_names
        self._cached_depth = 0
        self._fn_depth = 0
        self._loop_depth = 0

    def _visit_loop(self, node):
        self._loop_depth += 1
        try:
            self.generic_visit(node)
        finally:
            self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def _visit_scope(self, node):
        cached = any(_decorator_caches(d) for d in node.decorator_list)
        self._cached_depth += cached
        self._fn_depth += isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef))
        # a def nested in a loop runs at CALL time, not per iteration:
        # its body starts from loop depth 0
        outer_loops, self._loop_depth = self._loop_depth, 0
        try:
            QualnameVisitor._visit_scope(self, node)
        finally:
            self._loop_depth = outer_loops
            self._cached_depth -= cached
            self._fn_depth -= isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef))

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope

    def _add(self, node, rule, message):
        self.findings.append(Finding(
            file=self.file, line=node.lineno, rule=rule,
            symbol=self.qualname, message=message))

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if name in _KERNEL_BUILDERS and self._loop_depth > 0:
            self._add(node, "JAX006",
                      f"{name}() constructed inside a loop body rebuilds "
                      f"the kernel wrapper every iteration (per-window/"
                      f"per-rep retrace hazard); hoist the construction "
                      f"out of the loop or memoise the builder")
        if name in _JIT_NAMES:
            if self._fn_depth > 0 and self._cached_depth == 0:
                self._add(node, "JAX004",
                          "jax.jit() constructed inside an un-memoised "
                          "function body recompiles on every call; hoist "
                          "it or wrap the builder in functools.lru_cache")
        elif name is not None:
            bare = name.rsplit(".", 1)[-1]
            if bare in self.jitted_names and \
                    any(isinstance(a, ast.Lambda) for a in node.args):
                self._add(node, "JAX005",
                          f"inline lambda passed to jitted {bare}(): a "
                          f"fresh callable per call defeats the jit cache")
        self.generic_visit(node)


def lint_source(source: str, file: str) -> List[Finding]:
    """Run the JAX pass over one source text (fixture entry point)."""
    return _lint_tree(ast.parse(source, filename=file), file)


def _lint_tree(tree: ast.Module, file: str) -> List[Finding]:
    index = _ModuleIndex()
    index.visit(tree)
    traced = index.traced_set()
    findings: List[Finding] = []
    for name in sorted(traced):
        for node in index.defs.get(name, ()):
            # a def nested inside a traced def is covered by the outer
            # walk (symbol `outer.inner`); a standalone walk here would
            # report the same line twice under two symbols
            if any(enc in traced
                   for enc in index.enclosing.get(id(node), ())):
                continue
            lint = _TracedBodyLint(file, findings, prefix=name)
            for child in ast.iter_child_nodes(node):
                lint.visit(child)
    for lam in index.traced_lambdas:
        lint = _TracedBodyLint(file, findings, prefix="<lambda>")
        lint.visit(lam.body)
    _JitPerCallLint(file, findings, index.jitted_names).visit(tree)
    return sorted(set(findings))


def run_files(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        findings.extend(_lint_tree(parse_file(path), relpath(path)))
    return findings


@register("jax")
def run() -> List[Finding]:
    return run_files(iter_py_files(*SCAN_DIRS))
