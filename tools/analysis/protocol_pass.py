"""Pass 1 — protocol soundness for ProtocolSpec registries.

The static rendering of what typed-protocols proves with GADTs
(Network/TypedProtocol/Core.hs): every ProtocolSpec discovered under
ouroboros_tpu.network.protocols (import walk, not a hand list) is checked
for agency totality, transition well-formedness, reachability, and codec
coverage both ways.

Rules:
- PROTO001 agency-totality: a state named anywhere in the spec (init,
  transition source/target, declared targets of a branch callable) has no
  agency entry, or an agency entry names an unknown role.
- PROTO002 terminal-agency: a state with no outgoing transitions must have
  NOBODY agency, and a NOBODY state must have no outgoing transitions.
- PROTO003 reachability: every declared state is reachable from init_state.
- PROTO004 opaque-branch: a callable transition carries no statically
  declared `targets` (see typed.branch), so the graph can't be checked.
- PROTO005 codec-missing: a message named in `transitions` has no
  encode/decode registration in the paired codec.
- PROTO006 codec-orphan: a codec registration for a message no transition
  ever names (dead wire vocabulary).
- PROTO007 no-codec: a spec has no module codec paired by the
  SPEC/CODEC (or X_SPEC/X_CODEC) naming convention.
"""
from __future__ import annotations

import importlib
import pkgutil
from typing import Dict, List, Optional, Tuple

from . import Finding, register, relpath
from .astutil import assignment_line, parse_file

PROTOCOLS_PACKAGE = "ouroboros_tpu.network.protocols"
ROLES = ("client", "server", "nobody")


def spec_states(spec) -> set:
    """Every state the spec names anywhere."""
    states = set(spec.agency) | {spec.init_state}
    for (src, _msg), dst in spec.transitions.items():
        states.add(src)
        states.update(_dsts(dst))
    return states


def _dsts(dst) -> Tuple[str, ...]:
    """Static target states of one transition entry."""
    if callable(dst):
        return tuple(getattr(dst, "targets", ()))
    return (dst,)


def message_inventory(spec) -> set:
    """Message type names the transition relation uses — the wire
    vocabulary the codec must cover (and tests must roundtrip)."""
    return {msg for (_src, msg) in spec.transitions}


def check_spec(spec, codec, file: str, line: int, symbol: str
               ) -> List[Finding]:
    """Pure soundness check for one (spec, codec) pair; codec may be None.
    Usable directly on synthetic specs (the seeded-violation tests)."""
    f: List[Finding] = []

    def add(rule, message):
        f.append(Finding(file=file, line=line, rule=rule, symbol=symbol,
                         message=f"{spec.name}: {message}"))

    states = spec_states(spec)
    nobody = "nobody"

    # PROTO001 agency totality
    for st in sorted(states):
        if st not in spec.agency:
            add("PROTO001", f"state {st!r} has no agency entry")
    for st, role in sorted(spec.agency.items()):
        if role not in ROLES:
            add("PROTO001", f"state {st!r} has unknown agency {role!r}")

    outgoing: Dict[str, list] = {st: [] for st in states}
    for (src, msg), dst in spec.transitions.items():
        outgoing.setdefault(src, []).append((msg, dst))

    # PROTO002 terminal states <-> NOBODY agency
    for st in sorted(states):
        has_out = bool(outgoing.get(st))
        role = spec.agency.get(st)
        if not has_out and role is not None and role != nobody:
            add("PROTO002", f"terminal state {st!r} has agency {role!r}, "
                            f"expected 'nobody'")
        if has_out and role == nobody:
            add("PROTO002", f"state {st!r} has NOBODY agency but "
                            f"{len(outgoing[st])} outgoing transition(s)")

    # PROTO004 opaque branch callables
    for (src, msg), dst in sorted(spec.transitions.items(),
                                  key=lambda kv: (kv[0][0], kv[0][1])):
        if callable(dst) and not getattr(dst, "targets", ()):
            add("PROTO004", f"transition ({src!r}, {msg!r}) is a callable "
                            f"with no declared targets (use typed.branch)")

    # PROTO003 reachability from init_state
    seen = {spec.init_state}
    frontier = [spec.init_state]
    while frontier:
        st = frontier.pop()
        for _msg, dst in outgoing.get(st, ()):
            for nxt in _dsts(dst):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
    for st in sorted(states - seen):
        add("PROTO003", f"state {st!r} unreachable from init state "
                        f"{spec.init_state!r}")

    # PROTO005/006/007 codec coverage both ways
    if codec is None:
        add("PROTO007", "no codec paired with this spec "
                        "(SPEC/CODEC naming convention)")
    else:
        registered = {cls.__name__ for cls in codec.by_tag.values()}
        inventory = message_inventory(spec)
        for msg in sorted(inventory - registered):
            add("PROTO005", f"message {msg!r} used in transitions has no "
                            f"codec registration")
        for msg in sorted(registered - inventory):
            add("PROTO006", f"codec registers {msg!r} but no transition "
                            f"names it")
    return f


def discover(package: str = PROTOCOLS_PACKAGE
             ) -> List[Tuple[object, Optional[object], str, int, str]]:
    """Import-walk the protocols package; yield
    (spec, codec, repo-relative file, line, symbol) per ProtocolSpec."""
    from ouroboros_tpu.network.protocols.codec import Codec
    from ouroboros_tpu.network.typed import ProtocolSpec

    pkg = importlib.import_module(package)
    found = []
    seen_ids = set()
    for info in sorted(pkgutil.iter_modules(pkg.__path__),
                       key=lambda i: i.name):
        mod = importlib.import_module(f"{package}.{info.name}")
        tree = None
        for attr, val in sorted(vars(mod).items()):
            if not isinstance(val, ProtocolSpec) or id(val) in seen_ids:
                continue
            seen_ids.add(id(val))
            codec_attr = ("CODEC" if attr == "SPEC"
                          else attr[:-5] + "_CODEC"
                          if attr.endswith("_SPEC") else None)
            codec = getattr(mod, codec_attr, None) if codec_attr else None
            if codec is not None and not isinstance(codec, Codec):
                codec = None
            if tree is None:
                tree = parse_file(mod.__file__)
            found.append((val, codec, relpath(mod.__file__),
                          assignment_line(tree, attr),
                          f"{info.name}.{attr}"))
    return found


@register("protocol")
def run() -> List[Finding]:
    findings: List[Finding] = []
    for spec, codec, file, line, symbol in discover():
        findings.extend(check_spec(spec, codec, file, line, symbol))
    return findings
