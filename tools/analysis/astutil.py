"""Shared AST plumbing for the ouro-lint passes."""
from __future__ import annotations

import ast
import os
from typing import Iterable, Iterator, List, Optional, Tuple

from . import REPO_ROOT


def iter_py_files(*subdirs: str, exclude: Iterable[str] = (),
                  exclude_dirs: Iterable[str] = ()) -> Iterator[str]:
    """Yield absolute paths of .py files under repo-relative `subdirs`
    (an entry may also be a single repo-relative .py FILE, e.g. a
    top-level script like bench.py), skipping repo-relative paths in
    `exclude` and whole repo-relative directory prefixes in
    `exclude_dirs`."""
    excluded = {e.replace("/", os.sep) for e in exclude}
    dir_prefixes = tuple(d.rstrip("/").replace("/", os.sep) + os.sep
                         for d in exclude_dirs)
    for sub in subdirs:
        base = os.path.join(REPO_ROOT, sub)
        if os.path.isfile(base):
            if base.endswith(".py") and \
                    os.path.relpath(base, REPO_ROOT) not in excluded:
                yield base
            continue
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, REPO_ROOT)
                if rel in excluded or rel.startswith(dir_prefixes):
                    continue
                yield path


def parse_file(path: str) -> ast.Module:
    with open(path, "rb") as f:
        return ast.parse(f.read(), filename=path)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing def/class qualname, the way
    the baseline identifies findings.  Subclasses read `self.qualname`."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack) or "<module>"

    def _visit_scope(self, node):
        self._stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._stack.pop()

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope
    visit_ClassDef = _visit_scope


def assignment_line(tree: ast.Module, attr: str) -> int:
    """Line where module attribute `attr` is (last) assigned, or 1.

    Handles tuple targets (`SPEC, CODEC, X = wrap(...)`) too — used by the
    protocol pass to anchor registry findings back to source."""
    line = 1

    def targets(node):
        for t in getattr(node, "targets", None) or [node.target]:
            if isinstance(t, (ast.Tuple, ast.List)):
                yield from t.elts
            else:
                yield t

    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            for t in targets(node):
                if isinstance(t, ast.Name) and t.id == attr:
                    line = node.lineno
    return line
