"""Pass 4 — STM concurrency lint (the static half of ouro-race).

The dynamic half (simharness/race.py) finds unordered TVar access pairs
by exploring schedules; this pass finds the *idioms* that create them
before any schedule runs.  It walks everything under ouroboros_tpu/
except the simharness runtime implementation itself (core/stm/runtime/
io_runtime/race are the machinery being linted FOR, not WITH):

- CONC001 tvar-mutation-outside-atomically: `.set_notify(...)` calls and
  assignments to a `._value` attribute mutate a TVar without a
  transaction.  set_notify is the sanctioned runtime-internal escape
  hatch for non-sim-thread producers, so every live use carries a
  baseline justification explaining why the unordered write commutes.
  (A plain `self._value = ...` — defining one's OWN private attribute —
  is the standard Python idiom and does not fire; TVars are never `self`
  outside the excluded runtime.)
- CONC002 blocking-in-atomic: a blocking primitive (`await`, a channel
  `recv`/`collect`, `time.sleep`/`sim.sleep`) inside a transaction
  function.  Transactions are plain functions run atomically by the
  scheduler; blocking inside one stalls every thread and can never be
  rolled back.  Use `retry()`/`tx.check(...)` to block transactionally.
- CONC003 global-mutation-in-sim-thread: an async function (or a helper
  nested in one) declaring `global X` and assigning it — module-global
  state shared across sim threads without a TVar is invisible to both
  the STM wake-up machinery and the race detector's HB model.
- CONC004 unsupervised-fork: a bare-statement `spawn(...)` whose handle
  is discarded.  A thread nobody can join/poll/cancel leaks past the
  sim snapshot and its failure is silently swallowed (the reference
  links forked threads to a supervisor; ThreadNet polls every handle).
- CONC005 nested-atomically: calling `atomically` from inside a
  transaction function.  The sim would run the inner transaction's
  effect record as a *coroutine await inside a sync function* — it
  cannot work, and in GHC STM nested atomically is outright illegal.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from . import Finding, register, relpath
from .astutil import dotted_name, iter_py_files, parse_file

SCAN_DIRS = ("ouroboros_tpu",)
# the STM/runtime implementation: mutating TVar internals IS its job
RUNTIME_IMPL_DIR = "ouroboros_tpu/simharness"

_BLOCKING_LEAVES = {"recv", "collect"}
_SLEEP_CALLS = {"time.sleep", "sim.sleep", "sleep"}


def _tx_fn_nodes(call: ast.Call, local_defs: dict) -> list:
    """The transaction-function bodies reachable from an atomically(...)
    call: a direct lambda, or a bare Name resolving to a def in this
    file.  Attribute references (``self._tx_fn``, ``q.get``) are NOT
    resolved — bound STM-structure methods are trusted, and chasing a
    method reference to its class body needs type information an AST
    walk doesn't have; a method-valued tx fn is only linted where it is
    defined next to its atomically call as a local def."""
    out = []
    for arg in call.args[:1]:
        if isinstance(arg, ast.Lambda):
            out.append(arg)
        elif isinstance(arg, ast.Name) and arg.id in local_defs:
            out.append(local_defs[arg.id])
    return out


class _ConcLint(ast.NodeVisitor):
    def __init__(self, file: str):
        self.file = file
        self.findings: List[Finding] = []
        self._stack: List[str] = []
        self._async_depth = 0
        # bare name -> innermost def node seen (good enough for lint:
        # tx fns are defined next to their atomically call)
        self._defs: dict = {}
        self._linted_tx_bodies: set = set()

    @property
    def qualname(self) -> str:
        return ".".join(self._stack) or "<module>"

    def _add(self, node, rule, message, symbol: Optional[str] = None):
        self.findings.append(Finding(
            file=self.file, line=node.lineno, rule=rule,
            symbol=symbol or self.qualname, message=message))

    # -- scope tracking ------------------------------------------------------
    def _visit_scope(self, node, is_async: bool):
        self._defs[node.name] = node
        self._stack.append(node.name)
        self._async_depth += is_async
        try:
            if self._async_depth > 0:
                self._check_global_mutation(node)
            self.generic_visit(node)
        finally:
            self._async_depth -= is_async
            self._stack.pop()

    def visit_FunctionDef(self, node):
        self._visit_scope(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_scope(node, is_async=True)

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._stack.pop()

    # -- CONC003 -------------------------------------------------------------
    @staticmethod
    def _walk_own_scope(fn):
        """Walk fn's body WITHOUT descending into nested defs/lambdas:
        the same name there is a fresh local binding (and nested scopes
        get their own _check_global_mutation via _visit_scope)."""
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))

    def _check_global_mutation(self, fn) -> None:
        declared: set = set()
        for stmt in fn.body:
            if isinstance(stmt, ast.Global):
                declared.update(stmt.names)
        if not declared:
            return
        for stmt in self._walk_own_scope(fn):
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in declared:
                    self._add(stmt, "CONC003",
                              f"module-global {t.id!r} mutated from a sim "
                              f"thread without a TVar: invisible to STM "
                              f"wake-ups and the race detector; hold it "
                              f"in a TVar")
                    declared.discard(t.id)

    # -- CONC001 -------------------------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_value_write(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_value_write(node.target)
        self.generic_visit(node)

    def _check_value_write(self, target) -> None:
        if isinstance(target, ast.Attribute) and target.attr == "_value" \
                and not (isinstance(target.value, ast.Name)
                         and target.value.id == "self"):
            self._add(target, "CONC001",
                      "direct write to a TVar's ._value bypasses the "
                      "transaction log AND the STM wake-up; use "
                      "atomically() (or set_notify with a baseline "
                      "justification)")

    # -- calls: CONC001 set_notify, CONC004 spawn, CONC002/5 tx bodies -------
    def visit_Expr(self, node: ast.Expr):
        # a bare-statement spawn(...) discards the only handle to the
        # thread — CONC004.  spawn in any other position (assigned,
        # appended, awaited, returned) is assumed supervised.
        if isinstance(node.value, ast.Call):
            name = dotted_name(node.value.func)
            if name and name.rsplit(".", 1)[-1] == "spawn":
                self._add(node, "CONC004",
                          f"fork without a join/supervisor: {name}(...) "
                          f"discards the Async handle, so the thread "
                          f"can't be polled, cancelled or reaped — keep "
                          f"the handle and poll it (or hand it to a "
                          f"supervisor)")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = dotted_name(node.func)
        leaf = name.rsplit(".", 1)[-1] if name else ""
        if leaf == "set_notify":
            self._add(node, "CONC001",
                      f"{name}() mutates a TVar outside atomically(); "
                      f"sanctioned only for non-sim-thread producers "
                      f"with an order-insensitivity justification in "
                      f"the baseline")
        elif leaf == "atomically":
            for fn in _tx_fn_nodes(node, self._defs):
                if id(fn) not in self._linted_tx_bodies:
                    self._linted_tx_bodies.add(id(fn))
                    self._lint_tx_body(fn)
        self.generic_visit(node)

    def _lint_tx_body(self, fn) -> None:
        body = fn.body if isinstance(fn, ast.Lambda) else fn
        for sub in ast.walk(body):
            if sub is fn:
                continue
            if isinstance(sub, ast.Await):
                self._add(sub, "CONC002",
                          "await inside a transaction function: "
                          "transactions are atomic sync blocks; block "
                          "with retry()/tx.check() instead")
            elif isinstance(sub, ast.Call):
                sub_name = dotted_name(sub.func) or ""
                sub_leaf = sub_name.rsplit(".", 1)[-1]
                if sub_name in _SLEEP_CALLS:
                    self._add(sub, "CONC002",
                              f"{sub_name}() inside a transaction "
                              f"function stalls every sim thread; "
                              f"transactions must not block — use "
                              f"retry() against a timer TVar "
                              f"(new_timeout)")
                elif sub_leaf in _BLOCKING_LEAVES:
                    self._add(sub, "CONC002",
                              f"{sub_name}() is a blocking receive "
                              f"inside a transaction function; read "
                              f"through a TQueue/TMVar with retry() "
                              f"semantics instead")
                elif sub_leaf == "atomically":
                    self._add(sub, "CONC005",
                              "nested atomically inside a transaction "
                              "function: the inner transaction can "
                              "never run (sync context) and nesting is "
                              "illegal STM; merge into one transaction "
                              "or use tx.or_else")


def lint_source(source: str, file: str) -> List[Finding]:
    """Run the conc pass over one source text (fixture entry point)."""
    lint = _ConcLint(file)
    lint.visit(ast.parse(source, filename=file))
    return lint.findings


def run_files(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        lint = _ConcLint(relpath(path))
        lint.visit(parse_file(path))
        findings.extend(lint.findings)
    return findings


@register("conc")
def run() -> List[Finding]:
    return run_files(iter_py_files(*SCAN_DIRS,
                                   exclude_dirs=(RUNTIME_IMPL_DIR,)))
