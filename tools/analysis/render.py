"""Machine-readable ouro-lint output: --format json / sarif.

Text stays the CLI default; these renderers exist so CI annotates PRs
and editors ingest findings without scraping.  Both are pure functions
of a Report — no IO, no exit-code logic (that stays in __main__).

JSON is the tool's own stable schema (versioned, keys sorted); SARIF is
the minimal valid subset of SARIF 2.1.0 that GitHub code scanning and
VS Code's SARIF viewer accept: one run, one driver, explicit rule
metadata, one result per finding with a physical location.  Baselined
findings are emitted at level "note" with suppression metadata so
consumers can distinguish them from blocking ("error") findings; stale
baseline entries ride along in JSON (SARIF has no natural slot for a
finding that does NOT exist, so they are JSON-only).
"""
from __future__ import annotations

from typing import Dict, List

from . import Finding, Report

JSON_SCHEMA_VERSION = 1
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

# one-line rule descriptions surfaced as SARIF rule metadata; kept here
# (not in the pass modules) so the renderer never imports jax-adjacent
# pass code it does not need
_RULE_DESCRIPTIONS = {
    "PROTO": "ProtocolSpec soundness (agency/reachability/codec)",
    "JAX": "JAX hot-path hazard (host sync / retrace)",
    "SIM": "sim-determinism leak (real clock/IO/RNG in async code)",
    "CONC": "STM concurrency hazard (see tools/analysis/conc_pass.py)",
}


def _finding_dict(f: Finding) -> dict:
    return {"file": f.file, "line": f.line, "rule": f.rule,
            "symbol": f.symbol, "message": f.message}


def report_to_json(report: Report, strict: bool) -> dict:
    """The CLI's own schema: everything the text output says, typed."""
    blocking = bool(report.new) or (strict and bool(report.stale))
    return {
        "schema_version": JSON_SCHEMA_VERSION,
        "tool": "ouro-lint",
        "strict": strict,
        "blocking": blocking,
        "summary": {name: len(fs)
                    for name, fs in sorted(report.by_pass.items())},
        "new": [_finding_dict(f) for f in report.new],
        "baselined": [_finding_dict(f) for f in report.baselined],
        "stale": [{"pass": name, "file": key[0], "rule": key[1],
                   "symbol": key[2]} for name, key in report.stale],
    }


def _sarif_rules(findings: List[Finding]) -> List[dict]:
    rules: Dict[str, dict] = {}
    for f in findings:
        if f.rule in rules:
            continue
        prefix = f.rule.rstrip("0123456789")
        rules[f.rule] = {
            "id": f.rule,
            "shortDescription": {
                "text": _RULE_DESCRIPTIONS.get(prefix, f.rule)},
        }
    return [rules[r] for r in sorted(rules)]


def _sarif_result(f: Finding, baselined: bool) -> dict:
    res = {
        "ruleId": f.rule,
        "level": "note" if baselined else "error",
        "message": {"text": f"[{f.symbol}] {f.message}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.file,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.line, 1)},
            }}],
    }
    if baselined:
        res["suppressions"] = [{"kind": "external",
                                "justification": "baseline.json"}]
    return res


def report_to_sarif(report: Report) -> dict:
    findings = report.new + report.baselined
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "ouro-lint",
                "informationUri":
                    "tools/analysis/README.md",
                "rules": _sarif_rules(findings),
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": [_sarif_result(f, baselined=False)
                        for f in report.new]
                       + [_sarif_result(f, baselined=True)
                          for f in report.baselined],
        }],
    }
