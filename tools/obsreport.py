"""obsreport — human-readable summary of a bench round's observability
sections, and a live view of a running node's scrape endpoint.

    python -m tools.obsreport BENCH_r05.json
    python -m tools.obsreport MULTICHIP_r06.json
    python bench.py > out.json && python -m tools.obsreport out.json
    python -m tools.obsreport --live 127.0.0.1:9187 [--interval 5]
    python -m tools.obsreport --fleet fleet.json
    python -m tools.obsreport --flight /tmp/ouro-flight [--tail 20]

Accepts a raw bench JSON object (what `python bench.py` prints), a
harness record wrapping one under ``parsed`` (the committed
BENCH_r*.json files), or a MULTICHIP_rNN.json mesh-dryrun record
(``{n_devices, rc, tail}`` — the MULTICHIP_OBS/MESH_SCALING JSON lines
are recovered from the stored stdout tail and rendered as a mesh
section: devices, prewarm/compile attribution, per-shard padding waste,
and sharded vs single-device replay throughput when both legs are
recorded).  For a bench round it prints, in order:

- the headline (proofs/s, speedup vs the CPU baseline, rep spread);
- the per-phase table from the ``variance`` section — median / min /
  max / absolute and relative spread per replay phase across the timed
  reps, with the dominant phase (largest absolute spread) starred.
  This is the attributed form of the old bare "vrf spread 45%" warning:
  the starred row names WHERE the cross-rep seconds moved;
- the ``overlap`` section (ISSUE 8): host-seq seconds hidden under
  in-flight device windows, hidden fraction and producer permit stalls
  — cross-rep medians;
- the ``stream`` section (ISSUE 15), when the round ran the streaming
  disk->decode->verify engine: read-ahead depth, disk+decode seconds
  hidden under device verify, snapshot write/restore timings and the
  restart probe — rounds without one render unchanged;
- the precompute cache stats (hit/miss/device_fill/eviction);
- the registry metrics snapshot (the deterministic subset bench embeds).

Rounds recorded before the observability layer (ISSUE 7) lack the
``phases``/``variance``/``metrics`` sections and pre-ISSUE-8 rounds
lack ``overlap``; each missing section is reported as absent rather
than failing, so the CLI works across the whole BENCH_r*.json history.

``--live ADDR`` scrapes a running process's metrics endpoint
(observe/scrape.py, served over the project's own snocket/SDU
transport) and renders replay progress (blocks done / ETA / blocks per
sec / windows in flight / hidden fraction) plus p50/p95/p99 for every
latency histogram — repeat with ``--interval N``.

``--fleet PATH`` renders a FleetTelemetry report (the JSON dict a chaos
threadnet run leaves on ``ChaosResult.fleet``, ISSUE 14): time-to-50%/
95%-adoption quantiles, per-edge delivery latency, partition-healing
times, and the per-peer mux byte accounting.

``--flight DIR`` renders a flight-recorder dump directory
(observe/flight.py): the reason header, aggregated metric deltas, and
the last ``--tail`` span/event ring entries — post-mortems no longer
require hand-reading flight.jsonl.

Exit codes: 0 report printed, 2 unreadable/unrecognised input.
"""
from __future__ import annotations

import json
import sys
from typing import List, Optional

from ouroboros_tpu.observe.spans import PHASES  # jax-free

PHASE_ORDER = PHASES + ("other",)

OVERLAP_MEDIANS = (
    ("host_seq_secs_median", "host-seq total"),
    ("device_secs_median", "device drains"),
    ("host_hidden_secs_median", "host-seq hidden under device"),
    ("hidden_frac_median", "hidden fraction"),
    ("producer_stall_secs_median", "producer permit stalls"),
)


def load_bench(path: str) -> dict:
    """The bench result object from `path` — unwraps a harness record's
    ``parsed`` field and tolerates a list of parsed JSON lines (the
    replay headline is the dict carrying ``metric``)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc and "metric" not in doc:
        doc = doc["parsed"]
    if isinstance(doc, list):
        dicts = [d for d in doc if isinstance(d, dict) and "metric" in d]
        if not dicts:
            raise ValueError("no bench result object in JSON list")
        doc = dicts[-1]
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError("not a bench result (no 'metric' field)")
    return doc


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in row)) for row in rows]
    return lines


def _fmt_secs(v) -> str:
    return f"{v:.4f}" if isinstance(v, (int, float)) else "-"


def render(doc: dict) -> str:
    out: List[str] = []

    # -- headline -----------------------------------------------------------
    out.append(f"{doc.get('metric', '?')}: {doc.get('value', '?')} "
               f"{doc.get('unit', '')}".rstrip())
    if "vs_baseline" in doc:
        out.append(f"  vs CPU baseline: {doc['vs_baseline']}x"
                   f"  (reps={doc.get('reps', '?')}, "
                   f"rep spread={doc.get('spread', '?')})")
    bd = doc.get("breakdown")
    if bd:
        out.append(f"  breakdown: device {bd.get('device_secs')}s / "
                   f"host {bd.get('host_secs')}s")

    # -- phase variance -----------------------------------------------------
    out.append("")
    var = doc.get("variance") or {}
    per_phase = var.get("per_phase")
    if per_phase:
        out.append("per-phase seconds across timed reps "
                   "(* = largest absolute spread):")
        dom = var.get("dominant_phase")
        rows = []
        for ph in PHASE_ORDER:
            st = per_phase.get(ph)
            if st is None:
                continue
            rows.append([("*" if ph == dom else " ") + ph,
                         _fmt_secs(st.get("median")),
                         _fmt_secs(st.get("min")),
                         _fmt_secs(st.get("max")),
                         _fmt_secs(st.get("spread_secs")),
                         st.get("spread_rel", "-")])
        out += _table(rows, ["phase", "median", "min", "max",
                             "spread_s", "rel"])
        if dom is not None:
            out.append(f"largest cross-rep spread: '{dom}' "
                       f"({var.get('dominant_spread_secs')}s min->max) — "
                       f"the phase to blame for rep-to-rep variance")
    else:
        out.append("no 'variance' section (round predates the "
                   "observability layer)")

    # -- host/device overlap (ISSUE 8 section; ISSUE 9 renders it) ----------
    out.append("")
    ov = doc.get("overlap") or {}
    if any(k in ov for k, _ in OVERLAP_MEDIANS):
        reps = len(ov.get("per_rep") or ())
        out.append(f"pipelined-replay overlap (medians over "
                   f"{reps or '?'} reps):")
        rows = [[label, ov.get(key, "-")] for key, label in
                OVERLAP_MEDIANS if key in ov]
        out += _table(rows, ["quantity", "median"])
        hf = ov.get("hidden_frac_median")
        if isinstance(hf, (int, float)):
            out.append(f"{100 * hf:.0f}% of the host sequential pass ran "
                       f"while a window was in flight on device — the "
                       f"closer to 100%, the closer host time is to free")
    else:
        out.append("no 'overlap' section (round predates the threaded "
                   "producer/consumer replay attribution)")

    # -- streaming replay section (ISSUE 15) --------------------------------
    # rounds without one render unchanged: the section only appears once
    # a bench round ran the disk->decode->verify engine
    stream = doc.get("stream")
    if stream:
        out.append("")
        out += _render_stream(stream)

    # -- verification-service serve section (ISSUE 12) ----------------------
    serve = doc.get("serve")
    if serve:
        out.append("")
        out += _render_serve(serve)

    # -- precompute cache ---------------------------------------------------
    out.append("")
    pc = doc.get("precompute")
    if pc:
        out.append("precompute cache:")
        out += _table([[k, pc[k]] for k in sorted(pc)],
                      ["stat", "value"])
    else:
        out.append("no 'precompute' section")

    # -- metrics snapshot ---------------------------------------------------
    out.append("")
    snap = doc.get("metrics")
    if snap:
        out.append("metrics snapshot (deterministic subset):")
        rows = []
        for name in sorted(snap):
            v = snap[name]
            if isinstance(v, dict):       # histogram
                v = f"count={v.get('count')} sum={v.get('sum')}"
            rows.append([name, v])
        out += _table(rows, ["metric", "value"])
    else:
        out.append("no 'metrics' section")
    return "\n".join(out) + "\n"


def _render_stream(st: dict) -> List[str]:
    """The ``stream`` section of a bench round (ISSUE 15): the
    disk->decode->verify engine's read-ahead accounting (how many
    storage seconds hid under device verify), and the snapshot write /
    restore timings behind `db_analyser --resume`."""
    out: List[str] = []
    out.append(f"streaming replay (disk -> decode -> verify, read-ahead "
               f"{st.get('read_ahead', '?')} windows):")
    rows = [
        ["blocks streamed", st.get("blocks", "-")],
        ["chunks read", st.get("chunks_read", "-")],
        ["bytes read", st.get("bytes_read", "-")],
        ["era crossings in-stream", st.get("era_crossings", "-")],
        ["prefetch stalls (reader ahead)", st.get("prefetch_stalls",
                                                  "-")],
        ["disk+decode secs", _fmt_secs(st.get("disk_secs"))],
        ["  of which hidden under device", _fmt_secs(
            st.get("disk_hidden_secs"))],
    ]
    out += _table(rows, ["quantity", "value"])
    hf = st.get("disk_hidden_frac")
    if isinstance(hf, (int, float)):
        out.append(f"{100 * hf:.0f}% of disk+decode ran while a window "
                   f"was in flight on device — the read-ahead's hiding "
                   f"power (same reading as the host-seq overlap above)")
    snaps = st.get("snapshots_written")
    if snaps is not None:
        out.append(f"snapshots: {snaps} written in "
                   f"{_fmt_secs(st.get('snapshot_write_secs'))}s; "
                   f"restore scan {_fmt_secs(st.get('restore_secs'))}s"
                   + (f"; resumed from slot {st['resumed_from_slot']}"
                      if st.get("resumed_from_slot") is not None
                      else ""))
    restart = st.get("restart")
    if restart:
        out.append(f"restart probe: reopened from the tip snapshot in "
                   f"{_fmt_secs(restart.get('restore_secs'))}s, "
                   f"{restart.get('blocks_replayed', '?')} blocks "
                   f"re-replayed, state-hash parity "
                   f"{restart.get('state_hash_parity')}")
    return out


def _render_serve(serve: dict) -> List[str]:
    """The ``serve`` section of a bench round (ISSUE 12): request-latency
    quantiles of the coalescing service vs the unbatched CPU baseline,
    the coalesced-batch-size histogram, and the fallback / deadline-miss
    / back-pressure accounting across the three trace legs."""
    out: List[str] = []
    sat = serve.get("saturated") or {}
    out.append(f"verification service (seed {serve.get('seed', '?')}, "
               f"deadline {serve.get('deadline_secs', '?')}s"
               + (", modeled device costs" if serve.get("modeled_costs")
                  else ", measured device costs") + "):")
    if sat:
        out.append(f"  saturated: {sat.get('requests')} requests, "
                   f"{sat.get('proofs_per_sec')} proofs/s = "
                   f"{sat.get('vs_unbatched_cpu')}x the unbatched "
                   f"per-request CPU baseline "
                   f"({sat.get('cpu_unbatched_proofs_per_sec')} /s)")
        lq, cq = sat.get("latency") or {}, \
            sat.get("cpu_unbatched_latency") or {}
        rows = [["service", lq.get("p50", "-"), lq.get("p95", "-"),
                 lq.get("p99", "-")],
                ["cpu unbatched", cq.get("p50", "-"), cq.get("p95", "-"),
                 cq.get("p99", "-")]]
        out += _table(rows, ["request latency (s)", "p50", "p95", "p99"])
        within = sat.get("p95_within_deadline")
        out.append(f"  p95 within deadline: {within}; deadline misses "
                   f"{sat.get('deadline_misses')} "
                   f"({sat.get('deadline_miss_frac')})")
        hist = sat.get("batch_size_hist") or {}
        if hist:
            out.append("  coalesced batch sizes (size: flushes):")
            out += _table([[k, hist[k]] for k in
                           sorted(hist, key=lambda s: int(s))],
                          ["batch", "count"])
        svc = sat.get("service") or {}
        out.append(f"  device batches {svc.get('device_batches')} "
                   f"({svc.get('device_requests')} reqs) / CPU fallback "
                   f"{svc.get('fallback_batches')} "
                   f"({svc.get('fallback_requests')} reqs)")
    light = serve.get("light_load") or {}
    if light:
        out.append(f"  light load: {light.get('requests')} requests, "
                   f"device batches {light.get('device_batches')} "
                   f"(break-even n*={light.get('break_even_n')}; 0 = "
                   f"every flush took the CPU fallback), "
                   f"{light.get('fallback_requests')} fallback reqs")
    bp = serve.get("backpressure") or {}
    if bp:
        out.append(f"  back-pressure: {bp.get('requests')} requests vs "
                   f"queue {bp.get('max_queue')}: "
                   f"{bp.get('backpressure_waits')} blocked submits, "
                   f"{bp.get('completed')} completed")
    be = (serve.get("break_even") or {}).get("entries") or {}
    if be:
        rows = [[p, be[p].get("n_star"), be[p].get("cpu_secs_per_req"),
                 be[p].get("device_secs_batch")] for p in sorted(be)]
        out += _table(rows, ["primitive", "n*", "cpu s/req",
                             "device s/batch"])
    parity = all(leg.get("parity") for leg in (sat, light, bp) if leg)
    out.append(f"  verdict parity vs CpuRefBackend on every leg: "
               f"{parity}")
    return out


# ---------------------------------------------------------------------------
# MULTICHIP mesh-dryrun rounds (ISSUE 11)
# ---------------------------------------------------------------------------

def load_multichip(path: str) -> Optional[dict]:
    """The multichip harness record from `path`, or None when the file
    is not one (callers fall through to load_bench).  The MULTICHIP_OBS
    and MESH_SCALING JSON lines are parsed out of the stored tail under
    ``obs``/``scaling`` (None when the round died before printing them —
    the rc says how)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(doc, dict) or "rc" not in doc \
            or "n_devices" not in doc:
        return None
    out = {"n_devices": doc.get("n_devices"), "rc": doc.get("rc"),
           "ok": doc.get("ok"), "obs": None, "scaling": None}
    for line in (doc.get("tail") or "").splitlines():
        for marker, key in (("MULTICHIP_OBS ", "obs"),
                            ("MESH_SCALING ", "scaling")):
            i = line.find(marker)
            if i < 0:
                continue
            try:
                out[key] = json.loads(line[i + len(marker):])
            except json.JSONDecodeError:
                pass
    return out


def render_multichip(doc: dict) -> str:
    """Mesh section of a MULTICHIP round: run identity, compile
    attribution, the sharded pipelined replay (parity, throughput,
    per-shard occupancy/padding waste) and the single-device comparison
    leg when the round recorded one."""
    out: List[str] = []
    out.append(f"multichip dryrun: {doc.get('n_devices', '?')} devices, "
               f"rc={doc.get('rc')} "
               f"({'green' if doc.get('rc') == 0 else 'RED'})")
    obs = doc.get("obs")
    if not obs:
        out.append("no MULTICHIP_OBS line in the stored tail (the round "
                   "died before attribution, or predates ISSUE 6)")
        return "\n".join(out) + "\n"

    compile_rows = [[k, obs[k]] for k in sorted(obs)
                    if k.endswith("_compile_secs")]
    if compile_rows:
        out.append("")
        out.append("compile attribution (seconds outside timed regions):")
        out += _table(compile_rows, ["stage", "secs"])
    if "over_budget_after" in obs:
        out.append(f"OVER BUDGET after '{obs['over_budget_after']}' "
                   f"({obs.get('elapsed_secs')}s of "
                   f"{obs.get('budget_secs')}s)")

    sh = obs.get("sharded_replay")
    out.append("")
    if sh:
        out.append("sharded pipelined replay (the real chain, not the "
                   "prewarm window):")
        rows = [[k, sh[k]] for k in sorted(sh) if k != "padding"]
        out += _table(rows, ["field", "value"])
        pad = sh.get("padding") or {}
        if pad:
            out.append("per-shard occupancy / padding waste:")
            out += _table([[k, pad[k]] for k in sorted(pad)],
                          ["stat", "value"])
        single = obs.get("single_device_replay") or {}
        sp, dp = (single.get("proofs_per_sec"),
                  sh.get("proofs_per_sec"))
        if sp and dp:
            out.append(f"sharded vs single-device: {dp} vs {sp} proofs/s "
                       f"({dp / sp:.2f}x on this mesh)")
        elif dp:
            out.append("no single-device leg recorded (budget-gated); "
                       "sharded throughput stands alone")
    else:
        out.append("no sharded_replay section (round predates the "
                   "sharded pipelined replay, ISSUE 11)")

    scaling = doc.get("scaling")
    if scaling:
        out.append("")
        out.append(f"mesh scaling: wall {scaling.get('wall_secs')} / "
                   f"dispatches per window "
                   f"{scaling.get('dispatches_per_window')} "
                   f"(relative n-vs-1: "
                   f"{scaling.get('relative_wall_n_vs_1')})")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# --fleet: render a FleetTelemetry report (ISSUE 14)
# ---------------------------------------------------------------------------

def load_fleet(path: str) -> dict:
    """The fleet report dict from `path`; accepts the bare report or a
    wrapper carrying it under ``fleet`` (a dumped ChaosResult)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "adoption" not in doc \
            and isinstance(doc.get("fleet"), dict):
        doc = doc["fleet"]
    if not isinstance(doc, dict) or "adoption" not in doc \
            or "nodes" not in doc:
        raise ValueError("not a fleet report (no 'adoption'/'nodes')")
    return doc


def _fmt_dist(d: dict) -> List[str]:
    return [str(d.get("n", 0)), _fmt_secs(d.get("p50")),
            _fmt_secs(d.get("p95")), _fmt_secs(d.get("max"))]


def render_fleet(doc: dict) -> str:
    out: List[str] = []
    nodes = doc.get("nodes") or []
    ad = doc.get("adoption") or {}
    out.append(f"fleet telemetry: {len(nodes)} nodes, "
               f"{ad.get('blocks', 0)} blocks tracked "
               f"({ad.get('fully_adopted_blocks', 0)} adopted by every "
               f"node)")
    out.append("")
    out.append("block adoption (seconds from first adoption; "
               "quantiles over blocks):")
    rows = [["time to 50% of nodes"] + _fmt_dist(ad.get("time_to_50")
                                                 or {}),
            ["time to 95% of nodes"] + _fmt_dist(ad.get("time_to_95")
                                                 or {})]
    out += _table(rows, ["quantity", "blocks", "p50", "p95", "max"])

    edges = doc.get("per_edge_delivery") or {}
    out.append("")
    if edges:
        out.append("per-edge delivery latency (receiver first-header-"
                   "seen minus sender adoption, seconds):")
        rows = [[edge] + _fmt_dist(edges[edge]) for edge in sorted(edges)]
        out += _table(rows, ["edge", "n", "p50", "p95", "max"])
    else:
        out.append("no per-edge deliveries recorded")

    parts = doc.get("partitions") or []
    if parts:
        out.append("")
        out.append("partition healing (first cross-group delivery "
                   "after the window):")
        rows = [[p.get("start"), p.get("end"),
                 _fmt_secs(p.get("healed_after_secs"))
                 if p.get("healed_after_secs") is not None
                 else "NEVER"] for p in parts]
        out += _table(rows, ["start", "end", "healed after (s)"])

    mux = doc.get("mux") or {}
    out.append("")
    if mux:
        out.append("per-peer mux accounting (edge|side; bytes are SDU "
                   "payload bytes):")
        rows = []
        for key in sorted(mux):
            m = mux[key]
            rows.append([key, m.get("egress_bytes"),
                         m.get("egress_sdus"), m.get("ingress_bytes"),
                         m.get("ingress_sdus")])
        out += _table(rows, ["connection", "out B", "out SDU",
                             "in B", "in SDU"])
    else:
        out.append("no mux accounting in this report")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# --flight: render a flight-recorder dump directory (ISSUE 14)
# ---------------------------------------------------------------------------

def load_flight(dir_path: str) -> tuple:
    """(header, records) from DIR/flight.jsonl (observe/flight.py dump
    layout).  Raises on a missing/garbled dump."""
    import os
    path = os.path.join(dir_path, "flight.jsonl")
    header: Optional[dict] = None
    records: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if header is None and rec.get("kind") == "flight":
                header = rec
                continue
            records.append(rec)
    if header is None:
        raise ValueError(f"{path}: no flight header line")
    return header, records


def render_flight(header: dict, records: List[dict],
                  tail: int = 20) -> str:
    out: List[str] = []
    out.append(f"flight dump: {header.get('entries')} ring entries — "
               f"reason: {header.get('reason') or '(none)'}")

    # -- aggregated metric deltas -------------------------------------------
    deltas: dict = {}
    for r in records:
        if r.get("kind") != "metric":
            continue
        name, op, v = r.get("name"), r.get("op"), r.get("v")
        d = deltas.setdefault(name, {"inc": 0, "observe": 0,
                                     "set": None})
        if op == "inc":
            d["inc"] += v
        elif op == "observe":
            d["observe"] += 1
        elif op == "set":
            d["set"] = v
    out.append("")
    if deltas:
        out.append("metric deltas over the ring:")
        rows = []
        for name in sorted(deltas):
            d = deltas[name]
            what = []
            if d["inc"]:
                what.append(f"+{d['inc']}")
            if d["observe"]:
                what.append(f"{d['observe']} obs")
            if d["set"] is not None:
                what.append(f"last={d['set']}")
            rows.append([name, " ".join(what) or "-"])
        out += _table(rows, ["metric", "delta"])
    else:
        out.append("no metric entries in the ring")

    # -- span/event tail -----------------------------------------------------
    trail = [r for r in records if r.get("kind") in ("span", "event")]
    out.append("")
    out.append(f"last {min(tail, len(trail))} span/event entries "
               f"(of {len(trail)}):")
    for r in trail[-tail:]:
        if r.get("kind") == "span":
            out.append(f"  {r.get('t'):>14.6f}  span   "
                       f"[{r.get('cat')}] {r.get('name')} "
                       f"({(r.get('t1') - r.get('t0')):.6f}s)")
        else:
            detail = {k: v for k, v in r.items()
                      if k not in ("t", "kind")}
            out.append(f"  {r.get('t'):>14.6f}  event  "
                       f"{json.dumps(detail, sort_keys=True)[:120]}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# --live: render a scraped exposition (replay progress + latency quantiles)
# ---------------------------------------------------------------------------

PROGRESS_GAUGES = (
    ("ouro_replay_progress_blocks_done", "blocks done"),
    ("ouro_replay_progress_total_blocks", "total blocks"),
    ("ouro_replay_progress_windows_in_flight", "windows in flight"),
    ("ouro_replay_progress_blocks_per_sec", "blocks/sec"),
    ("ouro_replay_progress_eta_secs", "ETA (s)"),
    ("ouro_replay_progress_hidden_frac", "hidden fraction"),
    ("ouro_replay_progress_devices", "mesh devices"),
    ("ouro_replay_progress_padding_waste_frac", "padding waste frac"),
)


def render_live(parsed: dict) -> str:
    """One live frame from a parsed exposition: replay progress, then
    p50/p95/p99 of every histogram present (recomputed scraper-side from
    the cumulative buckets — byte-identical to the serving process's own
    quantiles for the same counts)."""
    from ouroboros_tpu.observe.export import (
        prom_histogram_quantiles, prom_histograms,
    )
    out: List[str] = []
    prog = [(label, parsed[key]) for key, label in PROGRESS_GAUGES
            if key in parsed]
    if prog:
        done = parsed.get("ouro_replay_progress_blocks_done")
        total = parsed.get("ouro_replay_progress_total_blocks")
        if total:
            out.append(f"replay progress: {done:.0f}/{total:.0f} blocks "
                       f"({100 * done / total:.1f}%)")
        out.append("")
        out += _table([[l, v] for l, v in prog], ["progress", "value"])
    else:
        out.append("no replay.progress.* gauges in this exposition")
    out.append("")
    hists = prom_histograms(parsed)
    if hists:
        rows = []
        for base, count in sorted(hists.items()):
            if not count:
                continue               # nothing observed yet: skip
            q = prom_histogram_quantiles(parsed, base)
            rows.append([base, int(count), q["p50"], q["p95"], q["p99"]])
        out.append("latency/size histograms (quantiles from scraped "
                   "buckets):")
        out += _table(rows, ["histogram", "count", "p50", "p95", "p99"])
    return "\n".join(out) + "\n"


def _live_once(addr: str) -> str:
    """One scrape over the project transport: host:port dials TCP, a
    /path dials the Unix socket."""
    from ouroboros_tpu.network.snocket import snocket_for
    from ouroboros_tpu.observe.scrape import scrape
    from ouroboros_tpu.simharness import io_run
    if addr.startswith("/"):
        target: object = addr
    else:
        host, port = addr.rsplit(":", 1)
        target = (host, int(port))
    return io_run(scrape(snocket_for(target), target))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m tools.obsreport",
        description="render a bench round's observability sections, or "
                    "--live: a running node's scrape endpoint")
    ap.add_argument("path", nargs="?", help="BENCH_rNN.json round file")
    ap.add_argument("--live", metavar="ADDR",
                    help="scrape host:port (or /unix/path) and render "
                         "replay progress + latency quantiles")
    ap.add_argument("--interval", type=float, default=0.0,
                    help="with --live: re-scrape every N seconds until "
                         "interrupted (default: once)")
    ap.add_argument("--fleet", metavar="PATH",
                    help="render a FleetTelemetry report JSON (a chaos "
                         "run's ChaosResult.fleet)")
    ap.add_argument("--flight", metavar="DIR",
                    help="render a flight-recorder dump directory")
    ap.add_argument("--tail", type=int, default=20,
                    help="with --flight: span/event tail length "
                         "(default 20)")
    args = ap.parse_args(sys.argv[1:] if argv is None else argv)
    modes = [m for m in (args.path, args.live, args.fleet, args.flight)
             if m is not None]
    if len(modes) != 1:
        ap.print_usage(sys.stderr)
        print("obsreport: give exactly one of PATH, --live ADDR, "
              "--fleet PATH or --flight DIR", file=sys.stderr)
        return 2
    if args.fleet:
        try:
            doc = load_fleet(args.fleet)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"obsreport: cannot read {args.fleet}: {e}",
                  file=sys.stderr)
            return 2
        sys.stdout.write(render_fleet(doc))
        return 0
    if args.flight:
        try:
            header, records = load_flight(args.flight)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"obsreport: cannot read flight dump {args.flight}: "
                  f"{e}", file=sys.stderr)
            return 2
        sys.stdout.write(render_flight(header, records, tail=args.tail))
        return 0
    if args.live:
        from ouroboros_tpu.observe.export import parse_prometheus_text
        try:
            while True:
                sys.stdout.write(
                    render_live(parse_prometheus_text(
                        _live_once(args.live))))
                sys.stdout.flush()
                if args.interval <= 0:
                    return 0
                import time
                time.sleep(args.interval)
                sys.stdout.write("\n")
        except KeyboardInterrupt:
            return 0
        except Exception as e:
            print(f"obsreport: cannot scrape {args.live}: {e}",
                  file=sys.stderr)
            return 2
    mc = load_multichip(args.path)
    if mc is not None:
        sys.stdout.write(render_multichip(mc))
        return 0
    try:
        doc = load_bench(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"obsreport: cannot read {args.path}: {e}", file=sys.stderr)
        return 2
    sys.stdout.write(render(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
