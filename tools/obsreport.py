"""obsreport — human-readable summary of a bench round's observability
sections.

    python -m tools.obsreport BENCH_r05.json
    python bench.py > out.json && python -m tools.obsreport out.json

Accepts either a raw bench JSON object (what `python bench.py` prints)
or a harness record wrapping one under ``parsed`` (the committed
BENCH_r*.json files).  Prints, in order:

- the headline (proofs/s, speedup vs the CPU baseline, rep spread);
- the per-phase table from the ``variance`` section — median / min /
  max / absolute and relative spread per replay phase across the timed
  reps, with the dominant phase (largest absolute spread) starred.
  This is the attributed form of the old bare "vrf spread 45%" warning:
  the starred row names WHERE the cross-rep seconds moved;
- the precompute cache stats (hit/miss/device_fill/eviction);
- the registry metrics snapshot (the deterministic subset bench embeds).

Rounds recorded before the observability layer (ISSUE 7) lack the
``phases``/``variance``/``metrics`` sections; each missing section is
reported as absent rather than failing, so the CLI works across the
whole BENCH_r*.json history.

Exit codes: 0 report printed, 2 unreadable/unrecognised input.
"""
from __future__ import annotations

import json
import sys
from typing import List, Optional

from ouroboros_tpu.observe.spans import PHASES  # jax-free

PHASE_ORDER = PHASES + ("other",)


def load_bench(path: str) -> dict:
    """The bench result object from `path` — unwraps a harness record's
    ``parsed`` field and tolerates a list of parsed JSON lines (the
    replay headline is the dict carrying ``metric``)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc and "metric" not in doc:
        doc = doc["parsed"]
    if isinstance(doc, list):
        dicts = [d for d in doc if isinstance(d, dict) and "metric" in d]
        if not dicts:
            raise ValueError("no bench result object in JSON list")
        doc = dicts[-1]
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError("not a bench result (no 'metric' field)")
    return doc


def _table(rows: List[List[str]], header: List[str]) -> List[str]:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in row)) for row in rows]
    return lines


def _fmt_secs(v) -> str:
    return f"{v:.4f}" if isinstance(v, (int, float)) else "-"


def render(doc: dict) -> str:
    out: List[str] = []

    # -- headline -----------------------------------------------------------
    out.append(f"{doc.get('metric', '?')}: {doc.get('value', '?')} "
               f"{doc.get('unit', '')}".rstrip())
    if "vs_baseline" in doc:
        out.append(f"  vs CPU baseline: {doc['vs_baseline']}x"
                   f"  (reps={doc.get('reps', '?')}, "
                   f"rep spread={doc.get('spread', '?')})")
    bd = doc.get("breakdown")
    if bd:
        out.append(f"  breakdown: device {bd.get('device_secs')}s / "
                   f"host {bd.get('host_secs')}s")

    # -- phase variance -----------------------------------------------------
    out.append("")
    var = doc.get("variance") or {}
    per_phase = var.get("per_phase")
    if per_phase:
        out.append("per-phase seconds across timed reps "
                   "(* = largest absolute spread):")
        dom = var.get("dominant_phase")
        rows = []
        for ph in PHASE_ORDER:
            st = per_phase.get(ph)
            if st is None:
                continue
            rows.append([("*" if ph == dom else " ") + ph,
                         _fmt_secs(st.get("median")),
                         _fmt_secs(st.get("min")),
                         _fmt_secs(st.get("max")),
                         _fmt_secs(st.get("spread_secs")),
                         st.get("spread_rel", "-")])
        out += _table(rows, ["phase", "median", "min", "max",
                             "spread_s", "rel"])
        if dom is not None:
            out.append(f"largest cross-rep spread: '{dom}' "
                       f"({var.get('dominant_spread_secs')}s min->max) — "
                       f"the phase to blame for rep-to-rep variance")
    else:
        out.append("no 'variance' section (round predates the "
                   "observability layer)")

    # -- precompute cache ---------------------------------------------------
    out.append("")
    pc = doc.get("precompute")
    if pc:
        out.append("precompute cache:")
        out += _table([[k, pc[k]] for k in sorted(pc)],
                      ["stat", "value"])
    else:
        out.append("no 'precompute' section")

    # -- metrics snapshot ---------------------------------------------------
    out.append("")
    snap = doc.get("metrics")
    if snap:
        out.append("metrics snapshot (deterministic subset):")
        rows = []
        for name in sorted(snap):
            v = snap[name]
            if isinstance(v, dict):       # histogram
                v = f"count={v.get('count')} sum={v.get('sum')}"
            rows.append([name, v])
        out += _table(rows, ["metric", "value"])
    else:
        out.append("no 'metrics' section")
    return "\n".join(out) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__.split("\n\n")[0] + "\n\n"
              "usage: python -m tools.obsreport BENCH_rNN.json",
              file=sys.stderr)
        return 2
    try:
        doc = load_bench(argv[0])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"obsreport: cannot read {argv[0]}: {e}", file=sys.stderr)
        return 2
    sys.stdout.write(render(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
