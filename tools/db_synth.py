#!/usr/bin/env python
"""db-synth — forge an on-disk chain to replay with db-analyser.

The role the reference's `db-converter` plays for its validate-mainnet CI
gate (ouroboros-consensus-byron `db-converter`,
ouroboros-consensus-byron/ouroboros-consensus-byron.cabal:82 +
.buildkite/validate-mainnet.sh): produce an ImmutableDB the analyser can
replay.

Two chain flavours:
  --protocol mock-praos   mock ledger + mock-Praos (1 VRF + 1 KES/header)
  --protocol shelley      TPraos + Shelley ledger — the BASELINE workload:
                          2 ECVRF proofs + 1 KES sig + 1 OCert Ed25519 sig
                          per header, Ed25519 tx witnesses per body
                          (BASELINE.md configs #2-#4).

Usage: python tools/db_synth.py --out DIR [--protocol shelley] [--blocks N]
       [--txs-per-block M] [--pools P] [--f NUM/DEN]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from fractions import Fraction

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def open_out_db(fs, args):
    """The output store: our native ImmutableDB, or a reference-format
    writer (`--format reference`: the .primary/.secondary/.chunk dialect of
    Impl/Index/{Primary,Secondary}.hs) behind the same append_block shape."""
    from ouroboros_tpu.storage.immutabledb import ImmutableDB
    if getattr(args, "format", "native") != "reference":
        return ImmutableDB.open(fs, args.chunk_size, validate_all=False)

    from ouroboros_tpu.storage.refformat import RefDbWriter
    from ouroboros_tpu.utils import cbor as _cbor

    class _RefShim:
        """ImmutableDB.append_block signature over RefDbWriter, computing
        the header-within-block span the secondary entries record."""

        def __init__(self):
            self._w = RefDbWriter(fs, args.chunk_size,
                                  epoch_length=args.epoch_length)

        def append_block(self, slot, block_no, h, prev_hash, data,
                         is_ebb=False):
            obj = _cbor.loads(data)
            hdr_enc = _cbor.dumps(obj[0])
            off = data.find(hdr_enc)
            if off < 0:
                # fail loudly at write time: a wrong header span in the
                # secondary index would only surface as downstream garbage
                raise RuntimeError(
                    f"block at slot {slot}: header re-encoding is not a "
                    f"substring of the block bytes; cannot record the "
                    f"header span in the reference secondary index")
            self._w.append_block(slot, h, data, is_ebb=is_ebb,
                                 header_offset=off,
                                 header_size=len(hdr_enc))

        def close(self):
            self._w.close()

    return _RefShim()


def synth_mock_praos(args) -> dict:
    from ouroboros_tpu.consensus.headers import ProtocolBlock, make_header
    from ouroboros_tpu.consensus.protocols.praos import (
        HotKey, Praos, PraosConfig, PraosNode, praos_forge_fields,
    )
    from ouroboros_tpu.crypto import ed25519_ref, kes as kes_mod
    from ouroboros_tpu.ledgers.mock import Tx, TxIn, TxOut
    from ouroboros_tpu.storage.fs import IoFS

    seed = args.seed.encode()

    def h(tag: bytes, i: int) -> bytes:
        return hashlib.blake2b(seed + tag + i.to_bytes(4, "big"),
                               digest_size=32).digest()

    n = args.nodes
    vrf_sks = [h(b"vrf", i) for i in range(n)]
    vrf_vks = [ed25519_ref.public_key(sk) for sk in vrf_sks]
    kes_seeds = [h(b"kes", i) for i in range(n)]
    kes_vks = [kes_mod.vk_of(args.kes_depth, s) for s in kes_seeds]
    pay_sks = [h(b"pay", i) for i in range(n)]
    pay_vks = [ed25519_ref.public_key(sk) for sk in pay_sks]

    cfg = PraosConfig(
        nodes=tuple(PraosNode(vrf_vks[i], kes_vks[i], 1) for i in range(n)),
        k=2160, f=float(Fraction(args.f)), epoch_length=args.epoch_length,
        kes_depth=args.kes_depth,
        slots_per_kes_period=max(
            1, (args.blocks * 4) // kes_mod.total_periods(args.kes_depth)))
    protocol = Praos(cfg)
    hot_keys = [HotKey(kes_mod.KesSignKey(args.kes_depth, s))
                for s in kes_seeds]

    genesis = {pay_vks[i].hex(): 10_000 for i in range(n)}
    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "config.json"), "w") as fh:
        json.dump({
            "protocol": "mock-praos",
            "k": cfg.k, "f": cfg.f, "epoch_length": cfg.epoch_length,
            "kes_depth": cfg.kes_depth,
            "slots_per_kes_period": cfg.slots_per_kes_period,
            "nodes": [{"vrf_vk": vrf_vks[i].hex(),
                       "kes_vk": kes_vks[i].hex(), "stake": 1}
                      for i in range(n)],
            "genesis": genesis,
            "chunk_size": args.chunk_size,
        }, fh, indent=2)

    fs = IoFS(args.out)
    db = open_out_db(fs, args)

    # spendable outputs per node, seeded from the genesis pseudo-tx whose
    # outputs MockLedger indexes in sorted(vk) order
    GEN = b"\x00" * 32
    spendable: dict[int, list] = {}
    for ix, vk in enumerate(sorted(pay_vks)):
        spendable[pay_vks.index(vk)] = [(GEN, ix, 10_000)]

    state = protocol.initial_chain_dep_state()
    prev = None
    slot = 0
    forged = 0
    t0 = time.time()
    while forged < args.blocks:
        view = None
        ticked = protocol.tick_chain_dep_state(state, view, slot)
        leader = None
        for i in range(n):
            pi = protocol.check_is_leader((i, vrf_sks[i]), slot, ticked,
                                          view)
            if pi is not None:
                leader = (i, pi)
                break
        if leader is None:
            slot += 1
            continue
        i, pi = leader
        body = []
        for t in range(args.txs_per_block):
            owner = (forged * args.txs_per_block + t) % n
            if not spendable[owner]:
                continue
            txid, ix, amount = spendable[owner].pop(0)
            tx = Tx((TxIn(txid, ix),), (TxOut(pay_vks[owner], amount),))
            sig = ed25519_ref.sign(pay_sks[owner], tx.txid)
            tx = Tx(tx.inputs, tx.outputs, ((pay_vks[owner], sig),))
            spendable[owner].append((tx.txid, 0, amount))
            body.append(tx)
        hdr = make_header(prev, slot, body, issuer=i)
        signed = praos_forge_fields(protocol, hot_keys[i], pi, hdr)
        block = ProtocolBlock(signed, tuple(body))
        db.append_block(block.slot, block.block_no, block.hash,
                        block.prev_hash, block.bytes)
        state = protocol.reupdate_chain_dep_state(ticked, signed, view)
        prev = signed
        forged += 1
        slot += 1
        if forged % 500 == 0:
            print(f"  forged {forged}/{args.blocks} "
                  f"({forged / (time.time() - t0):.0f} blocks/s)",
                  file=sys.stderr)
    if hasattr(db, "close"):
        db.close()              # flush the reference-format tail chunk
    return {"blocks": forged, "last_slot": slot - 1}


def synth_shelley(args) -> dict:
    """Forge a TPraos/Shelley chain: the flagship replay workload.

    Reference: the Shelley chain the db-analyser validate-mainnet path
    replays (tools/db-analyser/Block/Shelley.hs + Shelley/Protocol.hs:
    433-442 PRTCL verifies per header; Ledger.hs:279-284 witnesses per
    body)."""
    from ouroboros_tpu.consensus.headers import ProtocolBlock, make_header
    from ouroboros_tpu.consensus.ledger import ExtLedgerRules
    from ouroboros_tpu.crypto import kes as kes_mod
    from ouroboros_tpu.eras.shelley import (
        TPraosConfig, forge_tpraos_fields, make_shelley_tx,
        shelley_genesis_setup,
    )
    from ouroboros_tpu.storage.fs import IoFS

    f = Fraction(args.f)
    # KES periods must cover the whole chain
    slots_per_period = max(
        1, int(args.blocks * 2 / f)
        // kes_mod.total_periods(args.kes_depth) + 1)
    cfg = TPraosConfig(
        k=2160, f=f, epoch_length=args.epoch_length,
        slots_per_kes_period=slots_per_period,
        kes_depth=args.kes_depth,
        max_kes_evolutions=kes_mod.total_periods(args.kes_depth) - 2)
    protocol, ledger, pools = shelley_genesis_setup(
        args.pools, cfg, stake_per_pool=100_000,
        seed=args.seed.encode())

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "config.json"), "w") as fh:
        json.dump({
            "protocol": "shelley",
            "k": cfg.k, "f": str(f), "epoch_length": cfg.epoch_length,
            "slots_per_kes_period": cfg.slots_per_kes_period,
            "kes_depth": cfg.kes_depth,
            "max_kes_evolutions": cfg.max_kes_evolutions,
            "genesis_seed": "shelley-genesis",
            "genesis": {p["addr"].hex(): 100_000 for p in pools},
            "pools": [{"pool_id": p["keys"].pool_id.hex(),
                       "vrf_vk": p["keys"].vrf_vk.hex(),
                       "addr": p["addr"].hex()} for p in pools],
            "chunk_size": args.chunk_size,
        }, fh, indent=2)

    fs = IoFS(args.out)
    db = open_out_db(fs, args)

    ext = ExtLedgerRules(protocol, ledger)
    state = ext.initial_state()
    # spendable (txid, ix, amount) per pool owner, from the genesis pseudo-tx
    GEN = ledger.GENESIS_TXID
    gen_order = sorted(p["addr"] for p in pools)
    spendable = {i: [(GEN, gen_order.index(p["addr"]), 100_000)]
                 for i, p in enumerate(pools)}

    prev = None
    slot = 0
    forged = 0
    t0 = time.time()
    while forged < args.blocks:
        view = ledger.forecast_view(state.ledger, slot)
        ticked = protocol.tick_chain_dep_state(
            state.header.chain_dep_state, view, slot)
        lead = None
        for pi, p in enumerate(pools):
            lead = protocol.check_is_leader(p["can_be_leader"], slot,
                                            ticked, view)
            if lead is not None:
                leader_ix = pi
                break
        if lead is None:
            slot += 1
            continue
        p = pools[leader_ix]
        body = []
        for t in range(args.txs_per_block):
            owner = (forged * args.txs_per_block + t) % len(pools)
            if not spendable[owner]:
                continue
            txid, ix, amount = spendable[owner].pop(0)
            op = pools[owner]
            tx = make_shelley_tx(
                inputs=[(txid, ix)], outputs=[(op["addr"], amount)],
                certs=[], signing_keys=[op["keys"].addr_sk])
            spendable[owner].append((tx.txid, 0, amount))
            body.append(tx)
        hdr = make_header(prev, slot, body, issuer=0)
        signed = forge_tpraos_fields(protocol, p["hot_key"],
                                     p["can_be_leader"], lead, hdr)
        block = ProtocolBlock(signed, tuple(body))
        db.append_block(block.slot, block.block_no, block.hash,
                        block.prev_hash, block.bytes)
        state = ext.tick_then_reapply(state, block)
        prev = signed
        forged += 1
        slot += 1
        if forged % 500 == 0:
            print(f"  forged {forged}/{args.blocks} "
                  f"({forged / (time.time() - t0):.0f} blocks/s)",
                  file=sys.stderr)
    if hasattr(db, "close"):
        db.close()              # flush the reference-format tail chunk
    return {"blocks": forged, "last_slot": slot - 1}


def synth_cardano(args) -> dict:
    """Forge a chain crossing the full era ladder (BASELINE config #5
    shape, now Byron->Shelley->Allegra->Mary per Cardano/Block.hs:161-186):
    PBFT blocks + EBBs, a Byron update proposal naming the Shelley fork
    epoch, TPraos blocks, then configured-epoch hops into Allegra (a
    validity-interval tx exercises the timelock gate) and Mary (a minting
    tx exercises multi-asset) — all through the combinator."""
    from ouroboros_tpu.consensus.hardfork.combinator import ERA_FIELD
    from ouroboros_tpu.consensus.headers import ProtocolBlock, make_header
    from ouroboros_tpu.eras.byron import (
        CERT_UPDATE, byron_sign_header, make_byron_tx, make_ebb,
    )
    from ouroboros_tpu.eras.cardano import (
        ALLEGRA, BYRON, MARY, SHELLEY, cardano_setup,
    )
    from ouroboros_tpu.eras.shelley import (
        forge_tpraos_fields, make_shelley_tx, pool_id_of,
    )
    from ouroboros_tpu.storage.fs import IoFS

    epoch_length = args.epoch_length
    total_epochs = max(8, args.blocks // epoch_length)
    # Byron spans >= 2 epochs so the chain contains an EBB with a same-slot
    # Byron successor (the EBB layout the storage layer must handle)
    fork_epoch = max(2, total_epochs // 4)
    if getattr(args, "eras", "ladder") == "byron-shelley":
        # the two-era chain of the streaming-replay scenario (ISSUE 15):
        # Byron EBBs -> ONE translation -> a long Shelley tail, no
        # intra-Shelley hops — the minimal shape that still crosses the
        # hard fork mid-stream
        allegra_epoch = mary_epoch = None
    else:
        allegra_epoch = fork_epoch + max(1, total_epochs // 4)
        mary_epoch = allegra_epoch + max(1, total_epochs // 4)
    # KES periods must cover the whole chain (synth_shelley discipline):
    # cardano_setup's default 50 slots/period exhausts the depth-5 key's
    # 30 usable evolutions after ~1500 slots, capping chains well below
    # the >=10k-block streaming scenario.  Sized here and recorded in
    # config.json so db_analyser rebuilds the identical setup.
    from ouroboros_tpu.eras.shelley import TPraosConfig
    slots_per_kes_period = max(50, (args.blocks * 2) // 30 + 1)
    shelley_config = TPraosConfig(
        k=8, epoch_length=epoch_length,
        slots_per_kes_period=slots_per_kes_period,
        kes_depth=5, max_kes_evolutions=30)
    eras, rules, nodes = cardano_setup(
        args.pools, epoch_length=epoch_length,
        shelley_config=shelley_config, seed=args.seed.encode(),
        allegra_epoch=allegra_epoch, mary_epoch=mary_epoch)

    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "config.json"), "w") as fh:
        json.dump({
            "protocol": "cardano", "nodes": args.pools,
            "epoch_length": epoch_length, "seed": args.seed,
            "fork_epoch": fork_epoch, "allegra_epoch": allegra_epoch,
            "mary_epoch": mary_epoch, "chunk_size": args.chunk_size,
            "slots_per_kes_period": slots_per_kes_period,
        }, fh, indent=2)
    fs = IoFS(args.out)
    db = open_out_db(fs, args)

    byron_era, shelley_era = eras[0], eras[1]
    state = rules.initial_state()
    prev = None
    slot = 0
    forged = 0
    update_sent = False
    # one feature tx per new era (none when the ladder stops at Shelley)
    feature_todo = ({ALLEGRA, MARY} if allegra_epoch is not None
                    else set())
    t0 = time.time()

    def append(blk):
        db.append_block(blk.slot, blk.block_no, blk.hash, blk.prev_hash,
                        blk.bytes, is_ebb=bool(blk.header.get("ebb", 0)))

    while forged < args.blocks:
        view = rules.ledger.ledger_view(rules.ledger.tick(state.ledger,
                                                          slot))
        ticked_dep = rules.protocol.tick_chain_dep_state(
            state.header.chain_dep_state, view, slot)
        if ticked_dep.era == BYRON:
            if slot % epoch_length == 0 and slot > 0:
                ebb = make_ebb(prev, slot // epoch_length, epoch_length)
                ebb = ebb.with_fields(**{ERA_FIELD: BYRON})
                blk = ProtocolBlock(ebb, ())
                state = rules.tick_then_reapply(state, blk)
                append(blk)
                forged += 1
                prev = ebb
            leader_ix = byron_era.protocol.slot_leader(slot)
            node = nodes[leader_ix]
            body = []
            if not update_sent:
                body.append(make_byron_tx(
                    inputs=[], outputs=[],
                    certs=[(CERT_UPDATE, fork_epoch.to_bytes(8, "big"),
                            b"")],
                    signing_keys=[node["genesis_sk"]]))
                update_sent = True
            hdr = make_header(prev, slot, body, issuer=leader_ix)
            hdr = hdr.with_fields(**{ERA_FIELD: BYRON})
            hdr = byron_sign_header(node["delegate_sk"], hdr)
            blk = ProtocolBlock(hdr, tuple(body))
        else:
            era_ix = ticked_dep.era
            lead = node = None
            for node in nodes:
                lead = shelley_era.protocol.check_is_leader(
                    node["can_be_leader"], slot, ticked_dep.inner,
                    view.inner)
                if lead is not None:
                    break
            if lead is None:
                slot += 1
                continue
            # one feature tx per era entry: Allegra's validity interval,
            # Mary's mint — spending the forger's own crossing UTxO
            body = []
            if era_ix in feature_todo:
                owner_addr = node["addr"]
                entry = next((u for u in state.ledger.inner.utxo
                              if u[2] == owner_addr and not u[4]), None)
                if entry is not None:
                    t, i, _a, amt, _assets = entry
                    addr_vk = owner_addr
                    if era_ix == ALLEGRA:
                        tx = make_shelley_tx(
                            inputs=[(t, i)], outputs=[(owner_addr, amt)],
                            certs=[], signing_keys=[node["keys"].addr_sk],
                            validity=(0, slot + epoch_length))
                    else:                       # MARY: mint a native asset
                        aid = pool_id_of(addr_vk)
                        tx = make_shelley_tx(
                            inputs=[(t, i)],
                            outputs=[(owner_addr, amt - 1),
                                     (owner_addr, 1, ((aid, 5),))],
                            certs=[], signing_keys=[node["keys"].addr_sk],
                            mint=[(aid, 5)])
                    body.append(tx)
                    feature_todo.discard(era_ix)
            hdr = make_header(prev, slot, body, issuer=0)
            hdr = hdr.with_fields(**{ERA_FIELD: era_ix})
            hdr = forge_tpraos_fields(shelley_era.protocol, node["hot_key"],
                                      node["can_be_leader"], lead, hdr)
            blk = ProtocolBlock(hdr, tuple(body))
        state = rules.tick_then_reapply(state, blk)
        append(blk)
        prev = blk.header
        forged += 1
        slot += 1
        if forged % 500 == 0:
            print(f"  forged {forged}/{args.blocks} "
                  f"({forged / (time.time() - t0):.0f} blocks/s)",
                  file=sys.stderr)
    if hasattr(db, "close"):
        db.close()              # flush the reference-format tail chunk
    return {"blocks": forged, "last_slot": slot - 1,
            "fork_epoch": fork_epoch}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True, help="target directory")
    ap.add_argument("--protocol", default="mock-praos",
                    choices=["mock-praos", "shelley", "cardano"])
    ap.add_argument("--blocks", type=int, default=1000)
    ap.add_argument("--txs-per-block", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4,
                    help="mock-praos forgers")
    ap.add_argument("--pools", type=int, default=2,
                    help="shelley stake pools")
    ap.add_argument("--f", default="4/5",
                    help="active slot coefficient (fraction)")
    ap.add_argument("--epoch-length", type=int, default=500)
    ap.add_argument("--kes-depth", type=int, default=10)
    ap.add_argument("--chunk-size", type=int, default=100)
    ap.add_argument("--format", default="native",
                    choices=["native", "reference"],
                    help="on-disk dialect: our CBOR-indexed ImmutableDB or the reference .primary/.secondary layout")
    ap.add_argument("--eras", default="ladder",
                    choices=["ladder", "byron-shelley"],
                    help="cardano era span: the full "
                         "Byron->Shelley->Allegra->Mary ladder, or stop "
                         "at Shelley (the streaming-replay e2e shape)")
    ap.add_argument("--seed", default="db-synth")
    args = ap.parse_args()

    t0 = time.time()
    if args.protocol == "shelley":
        info = synth_shelley(args)
    elif args.protocol == "cardano":
        info = synth_cardano(args)
    else:
        info = synth_mock_praos(args)
    info.update({"protocol": args.protocol, "dir": args.out,
                 "synth_secs": round(time.time() - t0, 2)})
    print(json.dumps(info))


if __name__ == "__main__":
    main()
