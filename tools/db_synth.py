#!/usr/bin/env python
"""db-synth — forge an on-disk mock-Praos chain to replay with db-analyser.

The role the reference's `db-converter` plays for its validate-mainnet CI
gate (ouroboros-consensus-byron `db-converter`,
ouroboros-consensus-byron/ouroboros-consensus-byron.cabal:82 +
.buildkite/validate-mainnet.sh): produce an ImmutableDB the analyser can
replay.  The chain carries the full Shelley-shaped proof mix — one ECVRF
proof + one KES signature per header, Ed25519 tx witnesses per body
(BASELINE.md configs #2-#4).

Usage: python tools/db_synth.py --out DIR [--blocks N] [--txs-per-block M]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True, help="target directory")
    ap.add_argument("--blocks", type=int, default=1000)
    ap.add_argument("--txs-per-block", type=int, default=2)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--f", type=float, default=0.8)
    ap.add_argument("--epoch-length", type=int, default=500)
    ap.add_argument("--kes-depth", type=int, default=10)
    ap.add_argument("--chunk-size", type=int, default=100)
    ap.add_argument("--seed", default="db-synth")
    args = ap.parse_args()

    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    from ouroboros_tpu.consensus.headers import ProtocolBlock, make_header
    from ouroboros_tpu.consensus.protocols.praos import (
        HotKey, Praos, PraosConfig, PraosNode, praos_forge_fields,
    )
    from ouroboros_tpu.crypto import ed25519_ref, kes as kes_mod
    from ouroboros_tpu.ledgers.mock import Tx, TxIn, TxOut
    from ouroboros_tpu.storage.fs import IoFS
    from ouroboros_tpu.storage.immutabledb import ImmutableDB

    seed = args.seed.encode()

    def h(tag: bytes, i: int) -> bytes:
        return hashlib.blake2b(seed + tag + i.to_bytes(4, "big"),
                               digest_size=32).digest()

    n = args.nodes
    vrf_sks = [h(b"vrf", i) for i in range(n)]
    vrf_vks = [ed25519_ref.public_key(sk) for sk in vrf_sks]
    kes_seeds = [h(b"kes", i) for i in range(n)]
    kes_vks = [kes_mod.vk_of(args.kes_depth, s) for s in kes_seeds]
    pay_sks = [h(b"pay", i) for i in range(n)]
    pay_vks = [ed25519_ref.public_key(sk) for sk in pay_sks]
    ssl_keys = [Ed25519PrivateKey.from_private_bytes(sk) for sk in pay_sks]

    cfg = PraosConfig(
        nodes=tuple(PraosNode(vrf_vks[i], kes_vks[i], 1) for i in range(n)),
        k=2160, f=args.f, epoch_length=args.epoch_length,
        kes_depth=args.kes_depth,
        slots_per_kes_period=max(
            1, (args.blocks * 4) // kes_mod.total_periods(args.kes_depth)))
    protocol = Praos(cfg)
    hot_keys = [HotKey(kes_mod.KesSignKey(args.kes_depth, s))
                for s in kes_seeds]

    genesis = {pay_vks[i].hex(): 10_000 for i in range(n)}
    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "config.json"), "w") as fh:
        json.dump({
            "protocol": "mock-praos",
            "k": cfg.k, "f": cfg.f, "epoch_length": cfg.epoch_length,
            "kes_depth": cfg.kes_depth,
            "slots_per_kes_period": cfg.slots_per_kes_period,
            "nodes": [{"vrf_vk": vrf_vks[i].hex(),
                       "kes_vk": kes_vks[i].hex(), "stake": 1}
                      for i in range(n)],
            "genesis": genesis,
            "chunk_size": args.chunk_size,
        }, fh, indent=2)

    fs = IoFS(args.out)
    db = ImmutableDB.open(fs, args.chunk_size, validate_all=False)

    # spendable outputs per node, seeded from the genesis pseudo-tx whose
    # outputs MockLedger indexes in sorted(vk) order
    GEN = b"\x00" * 32
    spendable: dict[int, list] = {}
    for ix, vk in enumerate(sorted(pay_vks)):
        spendable[pay_vks.index(vk)] = [(GEN, ix, 10_000)]

    state = protocol.initial_chain_dep_state()
    prev = None
    slot = 0
    forged = 0
    t0 = time.time()
    while forged < args.blocks:
        view = None
        ticked = protocol.tick_chain_dep_state(state, view, slot)
        leader = None
        for i in range(n):
            pi = protocol.check_is_leader((i, vrf_sks[i]), slot, ticked,
                                          view)
            if pi is not None:
                leader = (i, pi)
                break
        if leader is None:
            slot += 1
            continue
        i, pi = leader
        body = []
        for t in range(args.txs_per_block):
            owner = (forged * args.txs_per_block + t) % n
            if not spendable[owner]:
                continue
            txid, ix, amount = spendable[owner].pop(0)
            tx = Tx((TxIn(txid, ix),), (TxOut(pay_vks[owner], amount),))
            sig = ssl_keys[owner].sign(tx.txid)
            tx = Tx(tx.inputs, tx.outputs, ((pay_vks[owner], sig),))
            spendable[owner].append((tx.txid, 0, amount))
            body.append(tx)
        hdr = make_header(prev, slot, body, issuer=i)
        signed = praos_forge_fields(protocol, hot_keys[i], pi, hdr)
        block = ProtocolBlock(signed, tuple(body))
        db.append_block(block.slot, block.block_no, block.hash,
                        block.prev_hash, block.bytes)
        state = protocol.reupdate_chain_dep_state(ticked, signed, view)
        prev = signed
        forged += 1
        slot += 1
        if forged % 500 == 0:
            print(f"  forged {forged}/{args.blocks} "
                  f"({forged / (time.time() - t0):.0f} blocks/s)",
                  file=sys.stderr)

    print(json.dumps({"blocks": forged, "last_slot": slot - 1,
                      "dir": args.out,
                      "synth_secs": round(time.time() - t0, 2)}))


if __name__ == "__main__":
    main()
