#!/usr/bin/env python
"""db-analyser — open an on-disk chain DB, replay it, report.

Reference: ouroboros-consensus-cardano/tools/db-analyser/ —
Main.hs:27-40,95-145 (CLI: db dir, block-type config, --onlyImmutableDB,
analysis selection), Analysis.hs (ShowSlotBlockNo / CountTxOutputs /
ShowBlockHeaderSize / OnlyValidation streaming every block through an
iterator), and the validate-mainnet CI gate (§3.5) that replays the whole
chain through the ledger.

TPU twist: `--validate full` replays through consensus/batch.py — the
VRF+KES+Ed25519 proofs of a `--window` of blocks verified as ONE device
batch per window — with `--backend {ref,openssl,jax}` selecting the
CryptoBackend.  This is the BASELINE.md harness: blocks/sec + proofs/sec
per backend, plus the final ledger state hash for replay-parity checks.

Full validation routes through the STREAMING replay engine
(ouroboros_tpu/storage/stream.py, ISSUE 15): a bounded read-ahead
prefetcher streams ImmutableDB chunks and decodes them on a background
thread while earlier windows verify, `--snapshot-every N` checkpoints
the verified ledger state every N slots (crash-consistent LedgerDB
snapshots), and `--resume` restarts from the newest usable snapshot
instead of genesis — the db-analyser validate-mainnet path made both
disk-streaming and restartable.

Usage:
  python tools/db_analyser.py DIR --analysis show-slot-block-no
  python tools/db_analyser.py DIR --analysis count-tx-outputs
  python tools/db_analyser.py DIR --analysis show-header-size
  python tools/db_analyser.py DIR --analysis validate \\
      [--validate reapply|full] [--backend ref|openssl|jax] [--window 256] \\
      [--snapshot-every SLOTS] [--resume] [--read-ahead W]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_db(db_dir: str):
    from ouroboros_tpu.consensus.headers import ProtocolBlock
    from ouroboros_tpu.consensus.ledger import ExtLedgerRules
    from ouroboros_tpu.storage.fs import IoFS
    from ouroboros_tpu.storage.immutabledb import ImmutableDB
    from ouroboros_tpu.utils import cbor

    with open(os.path.join(db_dir, "config.json")) as fh:
        cfg = json.load(fh)

    if cfg["protocol"] == "mock-praos":
        from ouroboros_tpu.consensus.protocols.praos import (
            Praos, PraosConfig, PraosNode,
        )
        from ouroboros_tpu.ledgers.mock import MockLedger, Tx
        protocol = Praos(PraosConfig(
            nodes=tuple(PraosNode(bytes.fromhex(nd["vrf_vk"]),
                                  bytes.fromhex(nd["kes_vk"]), nd["stake"])
                        for nd in cfg["nodes"]),
            k=cfg["k"], f=cfg["f"], epoch_length=cfg["epoch_length"],
            kes_depth=cfg["kes_depth"],
            slots_per_kes_period=cfg["slots_per_kes_period"]))
        ledger = MockLedger({bytes.fromhex(vk): amt
                             for vk, amt in cfg["genesis"].items()})
        tx_decode = Tx.decode
        tx_body_elems = None
    elif cfg["protocol"] == "cardano":
        from ouroboros_tpu.eras.cardano import (
            cardano_block_decode, cardano_setup,
        )
        shelley_config = None
        if "slots_per_kes_period" in cfg:
            # db_synth sized the KES period to the chain length
            # (long-chain DBs); mirror cardano_setup's defaults with
            # only that knob overridden
            from ouroboros_tpu.eras.shelley import TPraosConfig
            shelley_config = TPraosConfig(
                k=8, epoch_length=cfg["epoch_length"],
                slots_per_kes_period=cfg["slots_per_kes_period"],
                kes_depth=5, max_kes_evolutions=30)
        _eras, rules, _nodes = cardano_setup(
            cfg["nodes"], epoch_length=cfg["epoch_length"],
            shelley_config=shelley_config,
            seed=cfg["seed"].encode(),
            allegra_epoch=cfg.get("allegra_epoch"),
            mary_epoch=cfg.get("mary_epoch"))
        fs = IoFS(db_dir)
        db = _open_immutable(fs, cfg)

        def decode_cardano(raw: bytes):
            return cardano_block_decode(cbor.loads(raw))

        return db, rules, decode_cardano, cfg
    elif cfg["protocol"] == "shelley":
        from fractions import Fraction

        from ouroboros_tpu.eras.shelley import (
            ShelleyLedger, ShelleyTx, TPraos, TPraosConfig,
        )
        tcfg = TPraosConfig(
            k=cfg["k"], f=Fraction(cfg["f"]),
            epoch_length=cfg["epoch_length"],
            slots_per_kes_period=cfg["slots_per_kes_period"],
            kes_depth=cfg["kes_depth"],
            max_kes_evolutions=cfg["max_kes_evolutions"])
        protocol = TPraos(tcfg, cfg["genesis_seed"].encode())
        pools = {bytes.fromhex(p["pool_id"]): bytes.fromhex(p["vrf_vk"])
                 for p in cfg["pools"]}
        delegs = {bytes.fromhex(p["addr"]): bytes.fromhex(p["pool_id"])
                  for p in cfg["pools"]}
        ledger = ShelleyLedger(
            {bytes.fromhex(a): amt for a, amt in cfg["genesis"].items()},
            tcfg, pools, delegs)
        tx_decode = ShelleyTx.decode
        tx_body_elems = 6          # ShelleyTx: 6 body fields + witnesses
    else:
        raise SystemExit(f"unknown protocol {cfg['protocol']!r}")

    rules = ExtLedgerRules(protocol, ledger)
    fs = IoFS(db_dir)
    db = _open_immutable(fs, cfg)

    def decode(raw: bytes, _elems=tx_body_elems) -> ProtocolBlock:
        # span-retaining decode: header bytes / KES message / tx ids come
        # from raw slices instead of re-encoding (the replay host pass)
        return ProtocolBlock.from_bytes(raw, tx_decode=tx_decode,
                                        tx_body_elems=_elems)

    return db, rules, decode, cfg


def _open_immutable(fs, cfg):
    """Open either on-disk dialect: the reference's .primary/.secondary/
    .chunk layout (refformat.py; Impl/Index/{Primary,Secondary}.hs) is
    auto-detected by the presence of .primary index files, else our native
    CBOR-indexed ImmutableDB."""
    from ouroboros_tpu.storage import refformat
    from ouroboros_tpu.storage.immutabledb import ImmutableDB
    if refformat.is_reference_db(fs):
        return refformat.RefImmutableView(
            refformat.RefDbReader(fs, cfg.get("chunk_size", 100)))
    return ImmutableDB.open(fs, cfg.get("chunk_size", 100),
                            validate_all=False)


def make_backend(name: str):
    from ouroboros_tpu.crypto.backend import CpuRefBackend, OpensslBackend
    if name == "ref":
        return CpuRefBackend()
    if name == "openssl":
        return OpensslBackend()
    if name == "cpp":
        from ouroboros_tpu.crypto.cpp_backend import CppBackend
        return CppBackend()
    if name == "jax":
        from ouroboros_tpu.crypto.jax_backend import JaxBackend
        return JaxBackend()
    raise SystemExit(f"unknown backend {name}")


def analysis_show_slot_block_no(db, decode, out):
    for entry, raw in db.stream():
        b = decode(raw)
        out.write(f"{b.slot}\t{b.block_no}\t{b.hash.hex()[:16]}\n")


def analysis_count_tx_outputs(db, decode, out):
    total = blocks = txs = 0
    for entry, raw in db.stream():
        b = decode(raw)
        blocks += 1
        for tx in b.body:
            txs += 1
            total += len(tx.outputs)
    out.write(json.dumps({"blocks": blocks, "txs": txs,
                          "tx_outputs": total}) + "\n")


def analysis_show_header_size(db, decode, out):
    biggest = (0, None)
    for entry, raw in db.stream():
        b = decode(raw)
        size = len(b.header.bytes)
        if size > biggest[0]:
            biggest = (size, b.slot)
        out.write(f"{b.slot}\t{size}\n")
    out.write(f"# max header size {biggest[0]} at slot {biggest[1]}\n")


# proofs per header: mock-praos = VRF + KES; shelley = 2 VRF + KES + OCert;
# cardano = per era (Byron delegate sig | Shelley's 4; EBBs carry none)
def _cardano_hdr_proofs(b) -> int:
    if b.header.get("ebb"):
        return 0
    return 1 if b.header.get("hfc_era") == 0 else 4


HEADER_PROOFS = {"mock-praos": 2, "shelley": 4,
                 "cardano": _cardano_hdr_proofs}


def analysis_validate(db, rules, decode, backend_name: str, mode: str,
                      window: int, out, hdr_proofs: int = 2,
                      db_dir: str = None, snapshot_every: int = 0,
                      resume: bool = False, read_ahead: int = 4):
    backend = make_backend(backend_name) if mode == "full" else None
    hdr_count = hdr_proofs if callable(hdr_proofs) \
        else (lambda b, n=hdr_proofs: n)
    ext = rules.initial_state()
    counts = {"blocks": 0, "proofs": 0}
    stream_stats = None
    t0 = time.time()
    if mode == "reapply":
        for entry, raw in db.stream():
            b = decode(raw)
            counts["blocks"] += 1
            counts["proofs"] += hdr_count(b) + sum(len(tx.witnesses)
                                                   for tx in b.body)
            ext = rules.tick_then_reapply(ext, b)
    else:
        # the streaming engine: disk + decode on a prefetch thread,
        # DiskPolicy-driven snapshots, resume-from-latest-snapshot
        from ouroboros_tpu.storage import (
            DiskPolicy, IoFS, StreamConfig, StreamingReplayEngine,
        )

        def counting_decode(raw: bytes):
            b = decode(raw)
            counts["blocks"] += 1
            counts["proofs"] += hdr_count(b) + sum(len(tx.witnesses)
                                                   for tx in b.body)
            return b

        policy = DiskPolicy(
            snapshot_interval_slots=snapshot_every
            if snapshot_every > 0 else (1 << 62))
        engine = StreamingReplayEngine(
            IoFS(db_dir), db, rules, counting_decode, backend=backend,
            config=StreamConfig(
                window=window, read_ahead=read_ahead, policy=policy,
                resume=bool(resume),
                # plain validation stays read-only on the DB dir;
                # --resume alone still writes the tip checkpoint so the
                # NEXT run restarts instantly
                take_snapshots=snapshot_every > 0 or bool(resume)))
        res = engine.replay()
        if not res.all_valid:
            raise SystemExit(
                f"validation FAILED at block {res.n_valid}: {res.error}")
        ext = res.final_state
        stream_stats = res.stats
    secs = time.time() - t0
    blocks, proofs = counts["blocks"], counts["proofs"]
    out.write(json.dumps({
        "analysis": "validate", "mode": mode,
        "backend": backend_name if mode == "full" else "n/a",
        "window": window if mode == "full" else None,
        "blocks": blocks, "proofs": proofs,
        "secs": round(secs, 3),
        "blocks_per_sec": round(blocks / secs, 1),
        "proofs_per_sec": round(proofs / secs, 1),
        "state_hash": ext.ledger.state_hash().hex(),
        "tip_slot": ext.header.tip.slot if ext.header.tip else None,
        **({"stream": stream_stats} if stream_stats is not None else {}),
    }) + "\n")


def analyse_real_shelley(path: str, backend_name: str, out) -> None:
    """Parse + fully validate REAL Cardano bytes (a header or a block
    file in any of the reference's encodings: bare, tag-24, or the HFC
    era wrapper).  Shelley bytes get the complete PRTCL/BBODY crypto —
    both VRF verify equations, KES over the body slice, OCert, witness
    multi-verify — on the chosen backend; Byron bytes get structural
    parse + the blake2b header-hash construction (the Ed25519-BIP32
    extended-key scheme lives outside this repo).

    VRF inputs default to the reference test examples' fixed seeds
    (Test.Consensus.Shelley.Examples mkBytes 0/1); real-chain replay would
    derive them from slot + epoch nonce."""
    import hashlib

    from ouroboros_tpu.eras import byron_cbor as BY
    from ouroboros_tpu.eras import shelley_cbor as SC
    raw = open(path, "rb").read()
    for kind, parse in (("block", BY.parse_block),
                        ("header", BY.parse_header)):
        try:
            parsed = parse(raw)
        except (ValueError, IndexError, TypeError, KeyError):
            continue
        hdr = parsed.header if kind == "block" else parsed
        what = "EBB" if hdr.is_ebb else "main"
        loc = f"epoch {hdr.epoch}" if hdr.is_ebb \
            else f"epoch {hdr.epoch} slot {hdr.slot}"
        extra = f" txs {parsed.n_txs}" if kind == "block" else ""
        print(f"byron {what} {kind}: {loc} magic {hdr.magic}{extra}",
              file=out)
        try:
            print(f"header hash: {hdr.header_hash.hex()}", file=out)
        except ValueError:
            pass
        return
    backend = make_backend(backend_name)
    a0 = hashlib.blake2b(b"\x00", digest_size=32).digest()
    a1 = hashlib.blake2b(b"\x01", digest_size=32).digest()
    try:
        tx = SC.parse_tx(raw)
    except (ValueError, IndexError, TypeError, KeyError):
        tx = None
    if tx is not None:
        ok = SC.validate_tx(tx, backend)
        print(f"shelley tx: txid {tx.body_hash.hex()} "
              f"witnesses {len(tx.witnesses)}; "
              f"witness crypto [{backend.name}]: "
              f"{'ok' if ok else 'FAILED'}", file=out)
        return
    try:
        blk = SC.parse_block(raw)
    except ValueError:
        blk = None
    if blk is not None:
        b = blk.header.body
        print(f"shelley block: slot {b.slot} block_no {b.block_no} "
              f"txs {len(blk.txs)} "
              f"witnesses {sum(len(t.witnesses) for t in blk.txs)}",
              file=out)
        ok = SC.validate_block(blk, a0, a1, backend,
                               check_body_size=False)
        print(f"body hash: "
              f"{'ok' if blk.computed_body_hash() == b.body_hash else 'BAD'}"
              f"; full crypto [{backend.name}]: "
              f"{'ok' if ok else 'FAILED'}", file=out)
        return
    hdr = SC.parse_header(raw)
    b = hdr.body
    print(f"shelley header: slot {b.slot} block_no {b.block_no} "
          f"issuer {b.issuer_vkey.hex()[:16]} "
          f"protover {b.protover_major}.{b.protover_minor}", file=out)
    ok = SC.validate_header(hdr, a0, a1, backend)
    print(f"full crypto [{backend.name}]: {'ok' if ok else 'FAILED'}",
          file=out)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("db", help="DB directory (from db_synth or a node), "
                               "or a raw real-Shelley header/block file "
                               "with --analysis validate-real")
    ap.add_argument("--analysis", default="validate",
                    choices=["show-slot-block-no", "count-tx-outputs",
                             "show-header-size", "validate",
                             "validate-real"])
    ap.add_argument("--validate", default="full",
                    choices=["reapply", "full"],
                    help="reapply: no crypto (snapshot-replay path); "
                         "full: all proofs verified")
    ap.add_argument("--backend", default="openssl",
                    choices=["ref", "openssl", "cpp", "jax"])
    ap.add_argument("--window", type=int, default=256,
                    help="blocks per device batch (full validation)")
    ap.add_argument("--snapshot-every", type=int, default=0,
                    metavar="SLOTS",
                    help="checkpoint the verified ledger state every N "
                         "slots during full validation (crash-"
                         "consistent LedgerDB snapshots; 0 = never)")
    ap.add_argument("--resume", action="store_true",
                    help="restart full validation from the newest "
                         "usable snapshot instead of genesis")
    ap.add_argument("--read-ahead", type=int, default=4, metavar="W",
                    help="prefetch bound in windows for the streaming "
                         "engine (full validation)")
    args = ap.parse_args()

    if args.analysis == "validate-real":
        analyse_real_shelley(args.db, args.backend, sys.stdout)
        return

    db, rules, decode, cfg = load_db(args.db)
    out = sys.stdout
    if args.analysis == "show-slot-block-no":
        analysis_show_slot_block_no(db, decode, out)
    elif args.analysis == "count-tx-outputs":
        analysis_count_tx_outputs(db, decode, out)
    elif args.analysis == "show-header-size":
        analysis_show_header_size(db, decode, out)
    else:
        analysis_validate(db, rules, decode, args.backend, args.validate,
                          args.window, out,
                          hdr_proofs=HEADER_PROOFS.get(cfg["protocol"], 2),
                          db_dir=args.db,
                          snapshot_every=args.snapshot_every,
                          resume=args.resume,
                          read_ahead=args.read_ahead)


if __name__ == "__main__":
    main()
