#!/usr/bin/env python
"""ping — handshake + KeepAlive RTT probe against a running node.

The cardano-ping demo analog (network-mux/demo/cardano-ping.hs +
SURVEY.md §2 "mux demos"): dial an address through the Snocket layer, run
the version-negotiation handshake on protocol 0, then KeepAlive probes,
and print negotiated version + RTT statistics as one JSON line.

Usage:
  python tools/ping.py HOST PORT [--count N] [--magic M] [--unix PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def ping(snocket, addr, magic: int, count: int) -> dict:
    from ouroboros_tpu.network import node_to_node as n2n
    from ouroboros_tpu.network.mux import INITIATOR, CodecChannel, Mux
    from ouroboros_tpu.network.protocols import handshake as hs_proto
    from ouroboros_tpu.network.protocols import keepalive as ka_proto
    from ouroboros_tpu.network.typed import CLIENT, Session

    bearer = await snocket.connect(addr)
    mux = Mux(bearer, "ping.mux")
    mux.start()
    try:
        hs = Session(
            hs_proto.SPEC, CLIENT,
            CodecChannel(mux.channel(n2n.HANDSHAKE_NUM, INITIATOR),
                         hs_proto.CODEC))
        res = await hs_proto.client_propose(
            hs, n2n.node_to_node_versions(magic))
        if res[0] != "accepted":
            return {"ok": False, "refused": str(res[1])}
        _, version, params = res
        rtts: list = []
        ka = Session(
            ka_proto.SPEC, CLIENT,
            CodecChannel(mux.channel(n2n.KEEPALIVE_NUM, INITIATOR),
                         ka_proto.CODEC))
        await ka_proto.client_probe(ka, count, 0.05,
                                    on_rtt=rtts.append)
        return {
            "ok": True, "version": version,
            "params": {k: v for k, v in dict(params or {}).items()},
            "probes": len(rtts),
            "rtt_min_ms": round(min(rtts) * 1000, 3),
            "rtt_avg_ms": round(sum(rtts) / len(rtts) * 1000, 3),
            "rtt_max_ms": round(max(rtts) * 1000, 3),
        }
    finally:
        mux.stop()
        close = getattr(bearer, "close", None)
        if close:
            close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("host", nargs="?", default="127.0.0.1")
    ap.add_argument("port", nargs="?", type=int, default=3001)
    ap.add_argument("--unix", help="dial a Unix socket path instead")
    ap.add_argument("--count", type=int, default=5)
    ap.add_argument("--magic", type=int, default=0)
    args = ap.parse_args()

    from ouroboros_tpu.network.snocket import TcpSnocket, UnixSnocket
    from ouroboros_tpu.simharness import io_run

    if args.unix:
        snocket, addr = UnixSnocket(), args.unix
    else:
        snocket, addr = TcpSnocket(), (args.host, args.port)
    out = io_run(ping(snocket, addr, args.magic, args.count))
    print(json.dumps(out))
    if not out.get("ok"):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
