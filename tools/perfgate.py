"""perfgate — the measured BENCH trajectory as an ENFORCED gate.

    python -m tools.perfgate --check BENCH_r*.json
    python -m tools.perfgate BENCH_r01.json BENCH_r02.json ...

The repo's performance story lives in the BENCH_r01..rNN round files
(2.03x -> 5.30x -> 2.65x -> 5.66x -> 12.13x vs the CPU baseline so
far); until now that trajectory was prose in ROADMAP.md — a regression
like the r03 dip was only caught by a human reading the numbers.  This
tool turns it into a merge gate: the LATEST round is judged against the
rounds before it and the run fails (rc 1) on any of

- **vs_baseline drop**: latest ``vs_baseline`` below the best earlier
  round by more than ``--max-drop`` (default 0.25 — r03's 50% dip would
  have failed this gate the day it landed);
- **best-rep spread**: latest rep spread ((max-min)/median over timed
  reps) above ``--max-spread`` (default 0.45 — the BENCH_r05 "45% vrf
  spread" class of instability);
- **hidden fraction**: latest ``overlap.hidden_frac_median`` (the
  pipelined replay's host-under-device hiding, recorded since ISSUE 8)
  below ``--min-hidden-frac`` (default 0.25) — the producer/consumer
  overlap silently degrading back to additive host+device time.

Checks only apply where the round records the field (early rounds lack
spread/overlap sections), so the gate passes on the committed
r01..r05 history as-is and `bench --smoke` runs it in tier-1.

``--multichip MULTICHIP_r*.json`` additionally gates the MULTICHIP
trajectory (the mesh dryrun artifacts: ``{n_devices, rc, ok, tail}``
with a ``MULTICHIP_OBS {json}`` line in the stdout tail since ISSUE 6).
The latest multichip round must be green end to end:

- **rc**: exit code 0 — a timeout (rc 124) or budget overrun (rc 3) is
  a red round;
- **compile attribution**: the MULTICHIP_OBS line is present and
  carries at least one ``*_compile_secs`` field (a red with no
  attribution is the MULTICHIP_r05 failure mode the dryrun was
  rebuilt to prevent);
- **sharded replay parity**: the obs ``sharded_replay`` section reports
  ``state_hash_parity`` true (the real pipelined mesh replay, ISSUE 11).

The multichip checks only become BINDING once at least one recorded
round carries the ``sharded_replay`` section: historical rounds predate
the sharded pipelined replay (r01-r05 have no MULTICHIP_OBS at all, or
none with that section), and the gate reports their checks as skipped
instead of failing tier-1 retroactively.  From the first green sharded
round onward, a later red round fails the gate.

``--serve BENCH_r*.json`` gates the verification-service trajectory
(the ``serve`` section bench emits since ISSUE 12, recorded from r06
on) with the same binding pattern: once any recorded round carries a
``serve`` section, the latest round's saturated leg must hold
``vs_unbatched_cpu >= 5.0`` and ``p95_within_deadline`` — earlier
rounds report their checks as skipped.

Exit codes: 0 pass, 1 regression, 2 unreadable/unrecognised input.
One JSON verdict object is printed on stdout either way.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import List, Optional

# reuse obsreport's tolerant loader (raw bench JSON, harness-wrapped
# ``parsed``, JSON-line lists)
from tools.obsreport import load_bench

DEFAULT_MAX_DROP = 0.25
# rep-spread bound tightened 0.45 -> 0.35 (ISSUE 12): the GC-discipline
# fix (PR 8) and the ("vrff", m) autotune key (PR 11) removed the two
# known variance sources, so a 0.40-spread round is a regression again.
# Historic rounds were measured before those fixes and stay judged by
# the old bound — the tight one binds from r06 on.
DEFAULT_MAX_SPREAD = 0.35
LEGACY_MAX_SPREAD = 0.45
SPREAD_BINDS_FROM_ROUND = 6
DEFAULT_MIN_HIDDEN_FRAC = 0.25
# the ISSUE 12 acceptance bar the serve section was landed against:
# saturated coalescing must beat the unbatched per-request CPU baseline
# by 5x with p95 inside the deadline
SERVE_MIN_VS_UNBATCHED = 5.0


def _round_no(path: str) -> Optional[int]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def load_round(path: str) -> dict:
    """One trajectory point: the fields the gate judges, plus identity."""
    doc = load_bench(path)
    overlap = doc.get("overlap") or {}
    return {
        "path": os.path.basename(path),
        "round": _round_no(path),
        "metric": doc.get("metric"),
        "value": doc.get("value"),
        "vs_baseline": doc.get("vs_baseline"),
        "spread": doc.get("spread"),
        "hidden_frac": overlap.get("hidden_frac_median"),
    }


def check_trajectory(paths: List[str],
                     max_drop: float = DEFAULT_MAX_DROP,
                     max_spread: float = DEFAULT_MAX_SPREAD,
                     min_hidden_frac: float = DEFAULT_MIN_HIDDEN_FRAC
                     ) -> dict:
    """Judge the newest round of `paths` against the rest; returns the
    verdict dict ({"ok": bool, "checks": [...], ...}).  Raises ValueError
    on inputs that are not bench rounds (rc 2 at the CLI)."""
    if not paths:
        raise ValueError("no bench rounds given")
    rounds = [load_round(p) for p in paths]
    # newest last: by recorded round number when the filenames carry one,
    # else by the order given
    if all(r["round"] is not None for r in rounds):
        rounds.sort(key=lambda r: r["round"])
    latest, earlier = rounds[-1], rounds[:-1]
    checks: List[dict] = []

    def check(name: str, ok: Optional[bool], detail: str) -> None:
        checks.append({"check": name,
                       "result": ("skipped" if ok is None
                                  else "pass" if ok else "FAIL"),
                       "detail": detail})

    prev_best = max((r["vs_baseline"] for r in earlier
                     if r["vs_baseline"] is not None), default=None)
    if latest["vs_baseline"] is None or prev_best is None:
        check("vs_baseline", None, "field absent in latest or history")
    else:
        floor = prev_best * (1.0 - max_drop)
        check("vs_baseline", latest["vs_baseline"] >= floor,
              f"latest {latest['vs_baseline']}x vs best earlier "
              f"{prev_best}x (floor {floor:.3f}x at max_drop={max_drop})")

    if latest["spread"] is None:
        check("rep_spread", None, "no 'spread' field in latest round")
    else:
        # rounds measured before the r06 variance fixes are judged by
        # the legacy bound; the caller's (tighter) bound binds after
        rnd = latest["round"]
        bound = max_spread
        note = ""
        if rnd is not None and rnd < SPREAD_BINDS_FROM_ROUND:
            bound = max(max_spread, LEGACY_MAX_SPREAD)
            note = (f" (legacy bound: r{rnd:02d} predates the "
                    f"variance fixes; {max_spread} binds from "
                    f"r{SPREAD_BINDS_FROM_ROUND:02d})")
        check("rep_spread", latest["spread"] <= bound,
              f"latest rep spread {latest['spread']} vs allowed "
              f"{bound}{note}")

    if latest["hidden_frac"] is None:
        check("hidden_frac", None,
              "no 'overlap.hidden_frac_median' in latest round "
              "(pre-ISSUE-8 rounds lack it)")
    else:
        check("hidden_frac", latest["hidden_frac"] >= min_hidden_frac,
              f"latest hidden_frac {latest['hidden_frac']} vs floor "
              f"{min_hidden_frac}")

    return {"ok": all(c["result"] != "FAIL" for c in checks),
            "latest": latest["path"],
            "rounds": [{"path": r["path"],
                        "vs_baseline": r["vs_baseline"]} for r in rounds],
            "thresholds": {"max_drop": max_drop,
                           "max_spread": max_spread,
                           "min_hidden_frac": min_hidden_frac},
            "checks": checks}


# ---------------------------------------------------------------------------
# Verification-service gate (ISSUE 14 satellite over the ISSUE 12 section)
# ---------------------------------------------------------------------------

def check_serve(paths: List[str],
                min_vs_unbatched: float = SERVE_MIN_VS_UNBATCHED) -> dict:
    """Judge the newest round's ``serve`` section.  Binding only once
    some recorded round carries one (bench emits it from r06 on); the
    pre-service history reports skipped — the --multichip pattern."""
    if not paths:
        raise ValueError("no bench rounds given")
    rounds = []
    for p in paths:
        doc = load_bench(p)
        rounds.append({"path": os.path.basename(p),
                       "round": _round_no(p),
                       "serve": doc.get("serve")})
    if all(r["round"] is not None for r in rounds):
        rounds.sort(key=lambda r: r["round"])
    latest = rounds[-1]
    binding = any(r["serve"] for r in rounds)
    sat = (latest["serve"] or {}).get("saturated") or {}
    checks: List[dict] = []

    def check(name: str, ok: bool, detail: str) -> None:
        if not binding:
            result = "skipped"
            detail += " [advisory: no serve-section round recorded yet]"
        else:
            result = "pass" if ok else "FAIL"
        checks.append({"check": name, "result": result, "detail": detail})

    vs = sat.get("vs_unbatched_cpu")
    check("serve_vs_unbatched",
          vs is not None and vs >= min_vs_unbatched,
          f"latest saturated vs_unbatched_cpu {vs} vs floor "
          f"{min_vs_unbatched}")
    check("serve_p95_deadline", sat.get("p95_within_deadline") is True,
          f"latest saturated p95_within_deadline="
          f"{sat.get('p95_within_deadline')} "
          f"(deadline {(latest['serve'] or {}).get('deadline_secs')}s)")

    return {"ok": all(c["result"] != "FAIL" for c in checks),
            "latest": latest["path"],
            "binding": binding,
            "rounds": [{"path": r["path"],
                        "has_serve": bool(r["serve"])} for r in rounds],
            "checks": checks}


# ---------------------------------------------------------------------------
# MULTICHIP trajectory gate (ISSUE 11)
# ---------------------------------------------------------------------------

def load_multichip_round(path: str) -> dict:
    """One multichip trajectory point: the harness record's rc plus the
    MULTICHIP_OBS object recovered from the stored stdout tail (absent on
    rounds that died before printing it — exactly the red shape the rc
    check exists for)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "rc" not in doc:
        raise ValueError(f"{path}: not a multichip round (no 'rc' field)")
    obs = None
    for line in (doc.get("tail") or "").splitlines():
        marker = line.find("MULTICHIP_OBS ")
        if marker < 0:
            continue
        try:
            obs = json.loads(line[marker + len("MULTICHIP_OBS "):])
        except json.JSONDecodeError:
            pass          # truncated tail: treat as unattributed
    return {"path": os.path.basename(path),
            "round": _round_no(path),
            "rc": doc.get("rc"),
            "n_devices": doc.get("n_devices"),
            "obs": obs}


def _compile_attributed(obs: Optional[dict]) -> bool:
    return bool(obs) and any(k.endswith("_compile_secs")
                             and obs[k] is not None for k in obs)


def check_multichip(paths: List[str]) -> dict:
    """Judge the newest MULTICHIP round; returns a verdict dict like
    check_trajectory's.  Checks are binding only once some recorded
    round carries the ``sharded_replay`` obs section (see module doc)."""
    if not paths:
        raise ValueError("no multichip rounds given")
    rounds = [load_multichip_round(p) for p in paths]
    if all(r["round"] is not None for r in rounds):
        rounds.sort(key=lambda r: r["round"])
    latest = rounds[-1]
    binding = any(r["obs"] and "sharded_replay" in r["obs"]
                  for r in rounds)
    checks: List[dict] = []

    def check(name: str, ok: bool, detail: str) -> None:
        if not binding:
            result = "skipped"
            detail += " [advisory: no sharded-replay round recorded yet]"
        else:
            result = "pass" if ok else "FAIL"
        checks.append({"check": name, "result": result, "detail": detail})

    check("rc", latest["rc"] == 0,
          f"latest {latest['path']} rc={latest['rc']}")
    check("compile_attribution", _compile_attributed(latest["obs"]),
          "MULTICHIP_OBS line with *_compile_secs fields "
          + ("present" if _compile_attributed(latest["obs"]) else "MISSING"))
    sharded = (latest["obs"] or {}).get("sharded_replay") or {}
    check("sharded_replay_parity",
          sharded.get("state_hash_parity") is True,
          f"latest sharded_replay section: "
          f"{ {k: sharded[k] for k in sorted(sharded) if k != 'padding'} }"
          if sharded else "no sharded_replay section in latest round")

    return {"ok": all(c["result"] != "FAIL" for c in checks),
            "latest": latest["path"],
            "binding": binding,
            "rounds": [{"path": r["path"], "rc": r["rc"],
                        "attributed": _compile_attributed(r["obs"])}
                       for r in rounds],
            "checks": checks}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.perfgate",
        description="fail (rc 1) when the newest BENCH round regresses "
                    "the measured trajectory")
    ap.add_argument("paths", nargs="*", help="BENCH_rNN.json round files")
    ap.add_argument("--check", nargs="+", default=[], metavar="PATH",
                    help="additional round files (alias for positionals)")
    ap.add_argument("--max-drop", type=float, default=DEFAULT_MAX_DROP,
                    help="max fractional vs_baseline drop from the best "
                         f"earlier round (default {DEFAULT_MAX_DROP})")
    ap.add_argument("--max-spread", type=float,
                    default=DEFAULT_MAX_SPREAD,
                    help="max rep spread in the latest round "
                         f"(default {DEFAULT_MAX_SPREAD})")
    ap.add_argument("--min-hidden-frac", type=float,
                    default=DEFAULT_MIN_HIDDEN_FRAC,
                    help="min pipelined-replay hidden fraction "
                         f"(default {DEFAULT_MIN_HIDDEN_FRAC})")
    ap.add_argument("--multichip", nargs="+", default=[], metavar="PATH",
                    help="MULTICHIP_rNN.json round files: gate the mesh "
                         "dryrun trajectory (rc=0, compile attribution, "
                         "sharded replay parity) alongside — or instead "
                         "of — the BENCH rounds")
    ap.add_argument("--serve", nargs="+", default=[], metavar="PATH",
                    help="BENCH_rNN.json round files: gate the "
                         "verification-service serve section (saturated "
                         f"vs_unbatched >= {SERVE_MIN_VS_UNBATCHED}x, "
                         "p95 inside the deadline); rounds predating "
                         "the section report skipped")
    args = ap.parse_args(argv)
    paths = list(args.paths) + list(args.check)
    if not paths and not args.multichip and not args.serve:
        print("perfgate: no rounds given", file=sys.stderr)
        return 2
    verdict: dict = {"ok": True}
    try:
        if paths:
            verdict = check_trajectory(
                paths, max_drop=args.max_drop,
                max_spread=args.max_spread,
                min_hidden_frac=args.min_hidden_frac)
        if args.multichip:
            mc = check_multichip(args.multichip)
            verdict["multichip"] = mc
            verdict["ok"] = verdict["ok"] and mc["ok"]
        if args.serve:
            sv = check_serve(args.serve)
            verdict["serve"] = sv
            verdict["ok"] = verdict["ok"] and sv["ok"]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perfgate: cannot judge trajectory: {e}", file=sys.stderr)
        return 2
    print(json.dumps(verdict, indent=2, sort_keys=True))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
