#!/usr/bin/env python
"""Benchmark: Shelley-path db-validate replay, TPU batch backend vs
sequential CPU — the BASELINE.md north-star metric.

Prints ONE JSON line:
  {"metric": "shelley_replay_proofs_per_sec", "value": N,
   "unit": "proofs/s", "vs_baseline": N, ...}

Workload (BASELINE configs #2-#4 in one stream): a TPraos chain — per
header 2 ECVRF proofs + 1 KES signature + 1 OCert Ed25519 sig, per body
Ed25519 tx witnesses — replayed through consensus/batch.py
(validate_blocks_batched) with full proof verification and state-hash
parity asserted between backends.

Baseline: the same replay on the cpp backend (single-core C++ Ed25519 +
ECVRF, the libsodium-class stand-in; the reference validates sequentially
on exactly such a path — SURVEY.md §2 "TPU-relevant gap").  Falls back to
openssl if the cpp extension is unavailable.

Secondary metrics (stderr): primitive throughputs (Ed25519 batch e2e, VRF
batch, KES batch) and a host/device time breakdown of the replay.

Measurement discipline: every kernel choice is pinned in the warmup
phase (persistent fenced autotuner, crypto/autotune.py) and the tuners
are FROZEN around every timed region — a mid-bench retune raises instead
of silently skewing a rep (the BENCH_r05 VRF regression).  `--retune`
drops the persisted choices and re-measures.  `--smoke` runs a tiny
parity-only replay (1 rep, no timing assertions) — the tier-1 guard that
keeps the replay path honest between bench rounds.

`--mesh N` (ISSUE 11) additionally replays the same chain through the
sharded pipelined driver — ShardedJaxBackend over an N-device mesh, the
threaded producer/consumer pipeline with per-shard packed windows and
the device-side verdict fold — and reports sharded proofs/s (and the
per-shard padding waste) beside the single-device number under a
``sharded`` key.  In this container the mesh is N forced host-platform
XLA devices (the flag is set before jax initialises); on TPU the same
knob shards over the real chips.

Every round also runs a ``stream`` leg (ISSUE 15): the same chain
replayed FROM DISK through the streaming engine
(ouroboros_tpu/storage/stream.py) — bounded read-ahead prefetch +
periodic crash-consistent snapshots + a resumed restart — reporting how
many disk+decode seconds hid under device verify (`disk_hidden_frac`)
and the restore cost of a restart.

`--serve` (ISSUE 12) exercises the CAUGHT-UP path instead of the
syncing one: the adaptive micro-batching VerifyService
(crypto/batching.py) under seeded bursty Poisson arrival traces in
deterministic sim time — p50/p95/p99 request latency and proofs/s
versus the unbatched per-request CPU baseline, a light-load leg that
must take the CPU break-even fallback with ZERO device dispatches, and
a back-pressure leg against a tiny admission queue.  Results land under
a ``serve`` key; `--smoke` runs a scaled-down copy as a tier-1 gate.
"""
import argparse
import glob
import json
import os
import re
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# persistent XLA compilation cache: the big ladder kernels take 1-2 min to
# compile per shape; cached executables make repeat runs start instantly
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(tempfile.gettempdir(), "jax-ouro-cache"))

# 10k blocks (VERDICT r2: measure at the scale the claims are about) in
# windows of 1024 — per window ONE packed device dispatch carrying the
# 2048-proof VRF batch, the 4096-sig Ed25519 batch (OCert + KES leaves +
# witnesses) and the next-next window's 2048 betas, overlapped with the
# host sequential pass (consensus/batch.py software pipeline)
BLOCKS = 10000
TXS = 2
WINDOW = 1024
EPOCH_LEN = 600
# measurement discipline (VERDICT r3 next-step 1a): every timed quantity is
# the MEDIAN of >= REPS repetitions with the min/max spread reported; a
# single-shot number on this chip has ~30-50% run-to-run noise and cannot
# distinguish a 2x kernel win from weather
REPS = int(os.environ.get("BENCH_REPS", "5"))
CPU_REPS = int(os.environ.get("BENCH_CPU_REPS", "2"))
SPREAD_WARN = 0.30


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def median_spread(vals):
    """(median, spread) where spread = (max-min)/median."""
    med = statistics.median(vals)
    return med, ((max(vals) - min(vals)) / med if med else 0.0)


def check_spread(name, vals):
    med, spread = median_spread(vals)
    if spread > SPREAD_WARN:
        # min rides along (BENCH_r05 follow-up): on a noisy chip the min
        # is the best estimate of the workload's true cost — if min is
        # close to the median the spread is a slow-tail artifact, if the
        # median is close to max the warm path itself is unstable
        log(f"WARNING: {name} spread {100 * spread:.0f}% over {len(vals)} "
            f"reps exceeds {100 * SPREAD_WARN:.0f}% — treat the median "
            f"with suspicion, prefer min {min(vals):.3f}s vs median "
            f"{med:.3f}s (vals: {[round(v, 3) for v in vals]})")
    return med, spread


# the span phase vocabulary IS the bench phase schema — import it so a
# category added in observe/spans.py cannot silently fold into `other`
from ouroboros_tpu.observe.spans import PHASES as PHASE_ORDER  # noqa: E402


def _rep_phase_totals(observe, roots, rep_secs: float) -> dict:
    """One timed rep's seconds per phase from its drained span forest.
    `other` is the rep wall time no span claimed (host work outside the
    instrumented seams — result folding, python overhead)."""
    totals = observe.phase_totals(roots)
    out = {ph: round(totals.get(ph, 0.0), 4) for ph in PHASE_ORDER}
    claimed = sum(totals.values())
    out["other"] = round(max(0.0, rep_secs - claimed), 4)
    return out


def _phase_variance(rep_phases) -> dict:
    """Cross-rep stats per phase + the phase with the largest spread.

    Ranked by ABSOLUTE spread (max-min seconds): the phase contributing
    the most wall-clock variance to the rep totals — a ~0s phase with
    big relative jitter must not outrank the phase that actually moved
    the median (the BENCH_r05 '45% vrf spread' diagnosis, attributed)."""
    if not rep_phases:
        return {}
    per_phase = {}
    for ph in list(PHASE_ORDER) + ["other"]:
        vals = [d.get(ph, 0.0) for d in rep_phases]
        med, spread = median_spread(vals)
        per_phase[ph] = {"median": round(med, 4),
                         "min": round(min(vals), 4),
                         "max": round(max(vals), 4),
                         "spread_secs": round(max(vals) - min(vals), 4),
                         "spread_rel": round(spread, 3)}
    dominant = max(per_phase, key=lambda p: per_phase[p]["spread_secs"])
    return {"per_phase": per_phase, "dominant_phase": dominant,
            "dominant_spread_secs": per_phase[dominant]["spread_secs"]}


def _rep_overlap(observe, roots) -> dict:
    """One timed rep's host/device overlap attribution from its span
    forest (the pipelined replay's whole point, measured):

    * host_seq_secs       — producer-thread sequential-pass wall time
      (union of `window.host_seq` spans);
    * device_secs         — consumer-thread blocking drains (union of
      `window.drain` spans);
    * host_hidden_secs    — host-seq time that ran WHILE a window was in
      flight on device (k-th submit start .. k-th drain end; drains are
      FIFO, so sorted pairing is exact).  host+device stop being
      additive exactly when this approaches host_seq_secs;
    * hidden_frac         — host_hidden_secs / host_seq_secs;
    * producer_stall_secs — producer time parked on the permit gate
      (depth back-pressure): the pipeline's headroom indicator.
    """
    sp = observe.spans
    host = sp.merge_intervals(sp.intervals_of(roots,
                                              name="window.host_seq"))
    drains = sorted(sp.intervals_of(roots, name="window.drain"))
    subs = sorted(sp.intervals_of(roots, name="window.submit"))
    inflight = [(s[0], d[1]) for s, d in zip(subs, drains) if d[1] > s[0]]
    stall = sp.merge_intervals(sp.intervals_of(roots, cat="stall"))
    host_total = sum(t1 - t0 for t0, t1 in host)
    hidden = sp.overlap_seconds(host, inflight)
    return {
        "host_seq_secs": round(host_total, 4),
        "device_secs": round(sum(t1 - t0 for t0, t1 in
                                 sp.merge_intervals(drains)), 4),
        "host_hidden_secs": round(hidden, 4),
        "hidden_frac": round(hidden / host_total, 3) if host_total else 0.0,
        "producer_stall_secs": round(sum(t1 - t0 for t0, t1 in stall), 4),
    }


def _overlap_summary(rep_overlaps) -> dict:
    """Cross-rep medians of the per-rep overlap attribution."""
    if not rep_overlaps:
        return {}
    out = {"per_rep": rep_overlaps}
    for k in ("host_seq_secs", "device_secs", "host_hidden_secs",
              "hidden_frac", "producer_stall_secs"):
        out[k + "_median"] = round(
            statistics.median(r[k] for r in rep_overlaps), 4)
    return out


def bench_rounds():
    """Every recorded BENCH_r*.json as (round_no, parsed-result dict),
    ascending — the one loader for all history comparisons (harness
    wrapping unwrapped, unreadable files tolerated)."""
    out = []
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except Exception:
            continue
        out.append((int(m.group(1)), data.get("parsed", data)))
    return sorted(out)


def previous_bench():
    """Latest recorded round, for the primitives-vs-previous-round
    comparison the bench prints itself (VERDICT r3 next-step 1e)."""
    rounds = bench_rounds()
    return rounds[-1] if rounds else None


def synth_chain(tmp: str, extra: tuple = ()) -> str:
    d = os.path.join(tmp, "chain")
    t0 = time.time()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "db_synth.py"),
         "--out", d, "--protocol", "shelley", "--blocks", str(BLOCKS),
         "--txs-per-block", str(TXS), "--epoch-length", str(EPOCH_LEN),
         "--pools", "2", "--f", "4/5", *extra],
        capture_output=True, text=True)
    if r.returncode != 0:
        raise SystemExit(f"synth failed: {r.stderr[-2000:]}")
    log(f"synth: {BLOCKS} blocks in {time.time() - t0:.0f}s")
    return d


_DBA = None


def _dba():
    """The db_analyser module, loaded once (it is a script, not a
    package member)."""
    global _DBA
    if _DBA is None:
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "dba", os.path.join(REPO, "tools", "db_analyser.py"))
        _DBA = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_DBA)
    return _DBA


def load(db_dir):
    db, rules, decode, cfg = _dba().load_db(db_dir)
    blocks = [decode(raw) for _entry, raw in db.stream()]
    return rules, blocks


def load_stream_ctx(db_dir):
    """(fs, db, rules, decode) for the streaming engine — the on-disk
    half of what `load` materialises in memory."""
    from ouroboros_tpu.storage import IoFS
    db, rules, decode, _cfg = _dba().load_db(db_dir)
    return IoFS(db_dir), db, rules, decode


def replay(rules, blocks, backend, window: int):
    """Full-validation replay (software-pipelined when the backend
    supports async windows); returns (secs, state_hash, n_proofs)."""
    from ouroboros_tpu.consensus.batch import replay_blocks_pipelined
    ext = rules.initial_state()
    proofs = sum(4 + sum(len(tx.witnesses) for tx in b.body)
                 for b in blocks)
    t0 = time.perf_counter()
    res = replay_blocks_pipelined(rules, blocks, ext, backend=backend,
                                  window=window)
    if not res.all_valid:
        raise SystemExit(f"replay failed at block {res.n_valid}: "
                         f"{res.error}")
    secs = time.perf_counter() - t0
    return secs, res.final_state.ledger.state_hash(), proofs


class TimingBackend:
    """Wraps a CryptoBackend, accumulating wall time by seam:

    * device_secs   — blocking device waits: finish_window drains plus
      the synchronous batch verifies (caller-thread time actually spent
      waiting on results);
    * dispatch_secs — submit_window: host-side request packing + async
      dispatch.  In the producer/consumer replay this runs on the
      PRODUCER thread, overlapped with the consumer's drain — charging
      it to "device" (the r5 wrapper did) double-counted overlapped
      wall time and hid the packing cost once it moved off-thread.

    Each field has a single writer thread (dispatch: producer, device:
    consumer), so the unlocked accumulation is race-free."""

    _DEVICE_CALLS = ("verify_ed25519_batch", "verify_vrf_batch",
                     "verify_kes_batch", "verify_mixed",
                     "vrf_betas_batch", "finish_window")

    def __init__(self, inner):
        self._inner = inner
        self.device_secs = 0.0
        self.dispatch_secs = 0.0
        self.name = inner.name

    def _timed(self, fn, field, *a, **kw):
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        setattr(self, field,
                getattr(self, field) + time.perf_counter() - t0)
        return out

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name == "submit_window":
            return lambda *a, **kw: self._timed(attr, "dispatch_secs",
                                                *a, **kw)
        if name in self._DEVICE_CALLS:
            return lambda *a, **kw: self._timed(attr, "device_secs",
                                                *a, **kw)
        return attr


def _device_fence():
    """Drain the async dispatch queue so a timed rep never inherits the
    previous rep's in-flight device work (BENCH_r05: vrf primitive
    spread 45% came from un-fenced back-to-back dispatches).  Shares the
    autotuner's fence so both measurement disciplines stay identical."""
    from ouroboros_tpu.crypto.autotune import _fence
    _fence()


def _timed_reps(fn, reps=None, warmup=1):
    """Run fn() `warmup` un-timed times (pinning any kernel choice the
    shape needs), then `reps` timed reps with a block-until-ready fence
    before each and every autotuner FROZEN (a retune attempt inside a
    timed rep raises FrozenAutotunerError instead of poisoning the
    numbers); return the wall-times.

    Allocator/GC discipline (the r5 '45% vrf spread' fix, part 2): each
    rep's host garbage — result arrays, request lists, transfer staging
    buffers — is collected BEFORE the next rep's fence, and the cyclic
    GC is disabled inside the timed region, so a collection pause never
    lands inside a rep.  The transfer itself also shrank 130x (the
    fold-form verdict kernel), which removes the link-jitter term."""
    import gc

    from ouroboros_tpu.crypto import autotune
    for _ in range(warmup):
        fn()
    vals = []
    autotune.freeze_all()
    gc_was_enabled = gc.isenabled()
    try:
        for _ in range(reps or REPS):
            _device_fence()
            gc.collect()
            gc.disable()
            t0 = time.perf_counter()
            fn()
            vals.append(time.perf_counter() - t0)
            if gc_was_enabled:
                gc.enable()
    finally:
        if gc_was_enabled:
            gc.enable()
        autotune.thaw_all()
    return vals


def bench_primitives(jb):
    """Secondary metrics: primitive batch throughputs on the device —
    median of REPS with spread, per VERDICT r3's measurement discipline."""
    import hashlib

    from ouroboros_tpu.crypto import ed25519_ref, kes, vrf_ref
    from ouroboros_tpu.crypto.backend import Ed25519Req, KesReq, VrfReq
    out = {}
    # batch sizes match the replay's bucket shapes so the jit cache is
    # shared with the flagship run (fresh pallas shapes cost minutes)
    # Ed25519 (config #4 primitive)
    n = 4096
    sk = hashlib.sha256(b"bench-ed").digest()
    vk = ed25519_ref.public_key(sk)
    msgs = [b"m%06d" % i for i in range(n)]
    reqs = [Ed25519Req(vk, m, ed25519_ref.sign(sk, m)) for m in msgs]

    def run_ed():
        assert all(jb.verify_ed25519_batch(reqs))
    run_ed()                                # warm/compile (+ autotune)
    vals = _timed_reps(run_ed)              # + one fenced warmup rep
    med, spread = check_spread("ed25519 primitive", vals)
    out["ed25519_batch_per_sec"] = round(n / med, 1)
    out["ed25519_batch_per_sec_best"] = round(n / min(vals), 1)
    out["ed25519_spread"] = round(spread, 3)
    # VRF (config #2 primitive)
    nv = 2048
    vsk = hashlib.sha256(b"bench-vrf").digest()
    vvk = vrf_ref.public_key(vsk)
    vreqs = [VrfReq(vvk, b"a%d" % i, vrf_ref.prove(vsk, b"a%d" % i))
             for i in range(nv)]

    def run_vrf():
        assert all(jb.verify_vrf_batch(vreqs))
        # re-fence INSIDE the rep (ISSUE 11, the r04->r05 follow-up):
        # the verdict transfer syncs the fold output, but a rep must not
        # end while donated temporaries are still retiring — the next
        # rep's pre-fence would hide that tail OUTSIDE the timing and
        # re-expose it as run-to-run spread
        _device_fence()
    run_vrf()                               # warm/compile (+ autotune)
    vals = _timed_reps(run_vrf)             # + one fenced warmup rep
    med, spread = check_spread("vrf primitive", vals)
    out["vrf_batch_per_sec"] = round(nv / med, 1)
    out["vrf_batch_per_sec_best"] = round(nv / min(vals), 1)
    out["vrf_spread"] = round(spread, 3)
    # KES (config #3 primitive): hash path on host + leaf sigs on device
    nk = 4096
    ksk = kes.KesSignKey(6, hashlib.sha256(b"bench-kes").digest())
    kreqs = [KesReq(6, ksk.verification_key, 0, b"m%d" % i,
                    ksk.sign(b"m%d" % i).to_bytes()) for i in range(nk)]

    def run_kes():
        assert all(jb.verify_kes_batch(kreqs))
    run_kes()                               # warm/compile
    vals = _timed_reps(run_kes)             # + one fenced warmup rep
    med, spread = check_spread("kes primitive", vals)
    out["kes_batch_per_sec"] = round(nk / med, 1)
    out["kes_batch_per_sec_best"] = round(nk / min(vals), 1)
    out["kes_spread"] = round(spread, 3)
    return out


def compare_previous(prim):
    """Log primitive deltas vs the latest recorded round and return them
    for the output JSON ({} when no history)."""
    prev = previous_bench()
    if not prev:
        return {}
    rnd, doc = prev
    old = doc.get("primitives") or {}
    ratios = {}
    for k in ("ed25519_batch_per_sec", "vrf_batch_per_sec",
              "kes_batch_per_sec"):
        if k in old and k in prim and old[k]:
            delta = prim[k] / old[k]
            ratios[k] = round(delta, 3)
            log(f"vs BENCH_r{rnd:02d} {k}: {old[k]:.0f} -> {prim[k]:.0f} "
                f"({delta:.2f}x)")
    return {"vs_round": rnd, "ratios": ratios}


def vrf_attribution(prim):
    """The r04->r05 VRF primitive regression, attributed in-band (ISSUE
    11 satellite): if this round's vrf primitive throughput is below the
    best recorded round, the output JSON carries a note naming the two
    mechanical changes between the r04 and r05+ measurements — the
    primitive moved to the FOLD-form program (1 B/proof verdict transfer
    instead of 130 B point rows) and, since r06, autotunes under its own
    ("vrff", m) key instead of inheriting a choice pinned on the rows
    form the window composite measures.  Returns None when the round
    recovered (>= best)."""
    best = None
    for rnd, doc in bench_rounds():
        v = (doc.get("primitives") or {}).get("vrf_batch_per_sec")
        if v and (best is None or v > best[1]):
            best = (rnd, v)
    cur = prim.get("vrf_batch_per_sec")
    if best is None or cur is None or cur >= best[1]:
        return None
    return {
        "regressed_vs_round": best[0],
        "best_per_sec": best[1],
        "current_per_sec": cur,
        "note": ("verify_vrf_batch measures the fold-form program "
                 "(verify + on-device challenge fold, 1 B/proof "
                 "transfer) under its own ('vrff', m) autotune key; "
                 "r05 measured it under the rows-form ('vrf', m) key "
                 "pinned by the window composite AND shipped 130 "
                 "B/proof over the ~20 MB/s tunnel, which is both the "
                 "r04->r05 throughput drop and its 45% spread. If this "
                 "round is still below the best, the variance section "
                 "names the phase that moved."),
    }


def _cpu_backend():
    """Best sequential CPU baseline: cpp, else openssl (which itself
    degrades to pure Python without the binding)."""
    from ouroboros_tpu.crypto.backend import OpensslBackend
    try:
        from ouroboros_tpu.crypto.cpp_backend import CppBackend
        return CppBackend()
    except Exception as e:
        log(f"cpp backend unavailable ({e}); openssl fallback")
        return OpensslBackend()


def _smoke_verdict_parity(jb):
    """Mixed-batch verdict parity vs the pure-Python oracle, including
    deliberate corruptions of every primitive (bad sig / wrong alpha /
    tampered Merkle node / wrong period / truncated KES bytes).  Runs
    the batch twice — cold, then warm from the precomputation cache —
    and returns (parity_ok, warm_fill_dispatches, warm_kes_jobs): the
    warm pass must serve every key and hash path from the cache (zero
    fills, zero Blake2b jobs)."""
    import hashlib

    from ouroboros_tpu.crypto import ed25519_ref, kes, vrf_ref
    from ouroboros_tpu.crypto.backend import (
        CpuRefBackend, Ed25519Req, KesReq, VrfReq,
    )
    from ouroboros_tpu.crypto.precompute import GLOBAL_PRECOMPUTE_CACHE
    sk = hashlib.sha256(b"smoke-ed").digest()
    vk = ed25519_ref.public_key(sk)
    vsk = hashlib.sha256(b"smoke-vrf").digest()
    vvk = vrf_ref.public_key(vsk)
    ksk = kes.KesSignKey(4, hashlib.sha256(b"smoke-kes").digest())
    kvk = ksk.verification_key
    reqs = [Ed25519Req(vk, b"m0", ed25519_ref.sign(sk, b"m0")),
            Ed25519Req(vk, b"bad", ed25519_ref.sign(sk, b"good")),
            VrfReq(vvk, b"a0", vrf_ref.prove(vsk, b"a0")),
            VrfReq(vvk, b"bad-alpha", vrf_ref.prove(vsk, b"a1"))]
    good = ksk.sign(b"kmsg")
    tam = kes.KesSig(good.leaf_sig,
                     ((good.merkle[0][0], bytes(32)),) + good.merkle[1:])
    reqs += [KesReq(4, kvk, 0, b"kmsg", good.to_bytes()),
             KesReq(4, kvk, 0, b"kmsg", tam.to_bytes()),
             KesReq(4, kvk, 1, b"kmsg", good.to_bytes()),
             KesReq(4, kvk, 0, b"kmsg", b"\x00" * 7)]
    # two evolved periods: 5 distinct depth-4 hash paths = 20 jobs, so
    # the KES bucket lands on the composite shape the replay just
    # compiled (off-chip runs stay cheap)
    for period in (1, 2):
        ksk.evolve()
        reqs.append(KesReq(4, kvk, period, b"p%d" % period,
                           ksk.sign(b"p%d" % period).to_bytes()))
    want = CpuRefBackend().verify_mixed(reqs)
    # fold-mode parity FIRST, while the KES paths are still cold: the
    # fold submission then has the same (ne, nv, nb, nk) window shape as
    # the plain cold batch below, so ONE composite compile serves both
    # (a warm-KES fold would be a different nk=0 shape — a fresh
    # multi-minute XLA:CPU compile the tier-1 budget cannot afford).
    # Only the tiny verdict-fold program is a new compile.
    from ouroboros_tpu.crypto.backend import WindowVerdict
    verdict, _b = jb.finish_window(jb.submit_window(reqs, fold=True))
    fold_ok = (isinstance(verdict, WindowVerdict)
               and verdict.first_bad == (want.index(False)
                                         if False in want else None))
    # the fold run cached the KES hash-path outcomes; re-cold them so
    # the plain batch below exercises the same cold shape it always did
    GLOBAL_PRECOMPUTE_CACHE._kes.clear()
    got = jb.verify_mixed(reqs)                               # cold
    # warm-path probe WITHOUT another ~composite dispatch (each one is
    # ~a minute of XLA:CPU in the tier-1 container): the host split and
    # table assembly must now serve everything from the cache — zero
    # fill dispatches, zero Blake2b hash-path jobs.  The full warm
    # window re-verification runs in tests/test_precompute.py
    # (slow+device) and in the hardware bench every round.
    fills = GLOBAL_PRECOMPUTE_CACHE.device_fills
    (eds, _eo, vrfs, _vo, kes_msgs, _ex, checks, _n) = \
        jb._split_mixed_device(reqs)
    point_vks = [r.vk for r in reqs if not isinstance(r, KesReq)] + \
        [e.vk for e in eds]
    GLOBAL_PRECOMPUTE_CACHE.assemble(point_vks)
    warm_fills = GLOBAL_PRECOMPUTE_CACHE.device_fills - fills
    return (got == want, fold_ok, warm_fills,
            len(kes_msgs) + len(checks), reqs)


def smoke(blocks: int = 8, window: int = 8):
    """Tiny parity-only replay gate (tier-1): synth a small TPraos
    chain, replay it once on the CPU baseline and once on the JAX
    backend (1 rep, no timing assertions), assert state-hash parity,
    key reuse during the replay, and mixed-batch verdict parity with a
    host-level zero-warm-work probe.  This catches a silently broken
    replay path between bench rounds, not a slow one.  (The heavier
    cold-vs-warm full re-verification lives in tests/test_precompute.py
    's slow+device partition.)  Returns the result dict."""
    global BLOCKS, TXS, EPOCH_LEN
    from ouroboros_tpu.crypto.jax_backend import JaxBackend
    from ouroboros_tpu.crypto.precompute import GLOBAL_PRECOMPUTE_CACHE

    old = (BLOCKS, TXS, EPOCH_LEN)
    # empty bodies + depth-4 KES keep every device bucket at the shapes
    # the tier-1 suite already compiles (min_bucket 16, window 8)
    BLOCKS, TXS, EPOCH_LEN = blocks, 0, 500
    tmp = tempfile.mkdtemp(prefix="bench-smoke-")
    try:
        chain = synth_chain(tmp, extra=("--kes-depth", "4"))
        rules, blocks_l = load(chain)
        cpu = _cpu_backend()
        _clear_beta_cache()
        _, cpu_hash, n_proofs = replay(rules, blocks_l, cpu, window)
        jb = JaxBackend(min_bucket=16, use_pallas=False, autotune=False)
        fills0 = GLOBAL_PRECOMPUTE_CACHE.device_fills
        _clear_beta_cache()
        # the JAX replay takes the producer/consumer pipelined path with
        # the fold=True device verdict reduction (consensus/pipeline.py)
        # — so state-hash parity below IS the threaded-path parity gate.
        # Record spans for it: the overlap plumbing (host-seq hidden
        # under in-flight windows) must produce a well-formed
        # attribution even at smoke scale.
        from ouroboros_tpu import observe
        from ouroboros_tpu.observe import metrics as _om
        started0 = _om.counter("pipeline.producers_started",
                               always=True).value
        observe.spans.RECORDER.enable()
        try:
            observe.spans.RECORDER.drain()
            _, jax_hash, _ = replay(rules, blocks_l, jb, window)
            overlap_probe = _rep_overlap(observe,
                                         observe.spans.RECORDER.drain())
        finally:
            observe.spans.RECORDER.disable()
        producers_run = _om.counter("pipeline.producers_started",
                                    always=True).value - started0
        leaked = _smoke_producer_leak()
        # 2 pools: every window past the first runs on cached keys, so
        # the whole replay needs at most one fill dispatch per prep path
        # (ed window, vrf window) — more means the cache is not reused
        replay_fills = GLOBAL_PRECOMPUTE_CACHE.device_fills - fills0
        hash_ok = cpu_hash == jax_hash
        verdict_ok, fold_ok, warm_fills, warm_jobs, parity_reqs = \
            _smoke_verdict_parity(jb)
        snapshot_ok, disabled_writes, disabled_spans = \
            _smoke_observe(jb, parity_reqs)
        vrf_probe = _smoke_vrf_spread(jb)
        scrape_ok, scrape_leaked, scrape_q = _smoke_scrape()
        net_probe = _smoke_net_disabled()
        perfgate_ok, _perfgate_verdict = _smoke_perfgate()
        sharded_probe = _smoke_sharded_replay(rules, blocks_l)
        serve_probe = _smoke_serve()
        stream_probe = _smoke_stream(chain, jb, cpu_hash)
        result = {"metric": "bench_smoke", "value": 1.0,
                  "blocks": len(blocks_l), "proofs": n_proofs,
                  "state_hash_parity": bool(hash_ok),
                  "verdict_parity": bool(verdict_ok),
                  "fold_verdict_parity": bool(fold_ok),
                  "pipelined_producers_run": int(producers_run),
                  "producer_threads_leaked": int(leaked),
                  "overlap_probe": overlap_probe,
                  "vrf_spread_probe": vrf_probe,
                  "replay_fill_dispatches": int(replay_fills),
                  "warm_device_fills": int(warm_fills),
                  "warm_kes_jobs": int(warm_jobs),
                  "observe_snapshot_parses": bool(snapshot_ok),
                  "disabled_registry_writes": int(disabled_writes),
                  "disabled_spans_recorded": int(disabled_spans),
                  "scrape_roundtrip": bool(scrape_ok),
                  "scrape_threads_leaked": int(scrape_leaked),
                  "scrape_submit_drain_quantiles": scrape_q,
                  "net_disabled_probe": net_probe,
                  "perfgate_ok": bool(perfgate_ok),
                  "sharded_replay_smoke": sharded_probe,
                  "serve_probe": serve_probe,
                  "stream_probe": stream_probe,
                  "precompute": GLOBAL_PRECOMPUTE_CACHE.stats()}
        if not (hash_ok and verdict_ok and fold_ok
                and producers_run >= 1 and leaked == 0
                and overlap_probe["host_seq_secs"] > 0
                and vrf_probe["ok"]
                and warm_fills == 0
                and warm_jobs == 0 and replay_fills <= 3
                and snapshot_ok and disabled_writes == 0
                and disabled_spans == 0
                and scrape_ok and scrape_leaked == 0
                and net_probe["ok"]
                and perfgate_ok and sharded_probe["ok"]
                and serve_probe["ok"] and stream_probe["ok"]):
            result["value"] = 0.0
            print(json.dumps(result))
            raise SystemExit(f"bench --smoke parity failure: {result}")
        print(json.dumps(result))
        return result
    finally:
        BLOCKS, TXS, EPOCH_LEN = old
        shutil.rmtree(tmp, ignore_errors=True)


def _smoke_producer_leak() -> int:
    """Count still-alive replay producer threads after joining grace:
    the pipeline must never leak its thread — started/finished counters
    plus a live-thread sweep (the counters catch a producer that died
    un-joined, the sweep catches one that never exited)."""
    import threading

    from ouroboros_tpu.observe import metrics as _om
    started = _om.counter("pipeline.producers_started", always=True).value
    finished = _om.counter("pipeline.producers_finished",
                           always=True).value
    alive = sum(t.name == "ouro-replay-producer" and t.is_alive()
                for t in threading.enumerate())
    return (started - finished) + alive


# scheduler/OS noise floor for the smoke spread gate: relative spread is
# only meaningful once a rep dwarfs it, so the threshold relaxes by
# floor/median — at hardware-bench rep durations (>= 1s) it converges to
# the strict 0.30 the ISSUE 8 satellite demands, while the tier-1 CPU
# container's ~0.2s reps are judged against the noise they actually sit in
_SPREAD_NOISE_FLOOR_SECS = 0.15


def _smoke_vrf_spread(jb, reps: int = 5, rounds: int = 3) -> dict:
    """The vrf-spread regression gate (BENCH_r05's 45% follow-through):
    fenced, GC-disciplined reps of the warm VRF primitive — the exact
    discipline _timed_reps applies in the hardware bench — must show
    bounded run-to-run spread now that the verdict transfer is 1 B/proof
    (fold kernel) and collection pauses are kept out of timed regions.
    Best round of `rounds` wins (one noisy neighbour must not fail
    tier-1); threshold = 0.30 + noise_floor/median."""
    import hashlib

    from ouroboros_tpu.crypto import vrf_ref
    from ouroboros_tpu.crypto.backend import VrfReq
    vsk = hashlib.sha256(b"smoke-spread").digest()
    vvk = vrf_ref.public_key(vsk)
    reqs = [VrfReq(vvk, b"s%d" % i, vrf_ref.prove(vsk, b"s%d" % i))
            for i in range(8)]

    def run():
        assert all(jb.verify_vrf_batch(reqs))
    run()                       # compile + pin outside the timed rounds
    best = None
    for _ in range(rounds):
        med, spread = median_spread(_timed_reps(run, reps=reps, warmup=0))
        allowed = SPREAD_WARN + _SPREAD_NOISE_FLOOR_SECS / max(med, 1e-9)
        if best is None or spread - allowed < best[0] - best[1]:
            best = (spread, allowed, med)
        if spread < allowed:
            break
    spread, allowed, med = best
    return {"ok": bool(spread < allowed), "spread": round(spread, 3),
            "allowed": round(allowed, 3), "median_secs": round(med, 4),
            "reps": reps}


def _smoke_observe(jb, probe_reqs):
    """Observability gates for --smoke (ISSUE 7 acceptance):

    1. the registry snapshot round-trips (deterministic JSON) and the
       Prometheus exposition re-parses — the export path is never the
       thing that breaks between bench rounds;
    2. with observation DISABLED, a fully instrumented window performs
       ZERO gated registry writes and records zero spans (the NOP fast
       path actually is one).

    `probe_reqs` must be a batch whose window shape is ALREADY compiled
    (the verdict-parity batch): the probe may not spend a fresh XLA:CPU
    composite compile inside the tier-1 budget.

    Returns (snapshot_ok, disabled_writes, disabled_spans)."""
    from ouroboros_tpu import observe
    from ouroboros_tpu.crypto.precompute import GLOBAL_PRECOMPUTE_CACHE

    # re-cold the KES hash-path outcomes: the verdict-parity probe left
    # them warm, and a warm-KES batch takes the DIFFERENT zero-KES-job
    # ('win', ne, nv, nb, 0) composite shape — a fresh multi-minute
    # XLA:CPU compile smoke never pins (measured ~160s of the tier-1
    # budget).  Cold, the batch reuses the parity probe's compiled
    # shape AND exercises more instrumented seams (Blake2b jobs, cache
    # fills) under the disabled flag — a stronger zero-write probe.
    GLOBAL_PRECOMPUTE_CACHE._kes.clear()
    reg = observe.metrics.registry()
    rec = observe.spans.RECORDER
    try:
        snap = json.loads(reg.snapshot_json())
        prom = observe.export.parse_prometheus_text(
            observe.export.prometheus_text(reg))
        snapshot_ok = isinstance(snap, dict) and len(prom) >= len(snap)
    except Exception as e:
        log(f"observe snapshot failed to parse: {e!r}")
        snapshot_ok = False
    # the disabled-observation probe: run an instrumented hot-path
    # window (spans + gated counters on every seam) with everything off
    was_reg, was_rec = reg.enabled, rec.enabled
    reg.disable()
    rec.disable()
    try:
        writes0, roots0 = reg.data_writes, len(rec.roots)
        jb.verify_mixed(probe_reqs)
        with observe.span("probe", cat="sync"):
            pass
        disabled_writes = reg.data_writes - writes0
        disabled_spans = len(rec.roots) - roots0
    finally:
        reg.enabled, rec.enabled = was_reg, was_rec
    return snapshot_ok, disabled_writes, disabled_spans


def _smoke_scrape():
    """Scrape-endpoint smoke (ISSUE 9): serve the process registry over
    the project's own snocket/SDU transport inside a deterministic sim,
    scrape it, and re-derive latency quantiles from the exposition.  The
    pipelined replay that just ran populated `pipeline.submit_drain_secs`
    — the scraped p50/p95/p99 must come back finite and ordered — and
    the sim must wind down with ZERO leaked threads (the clean-shutdown
    contract of ScrapeServer/PeriodicEmitter).

    Returns (ok, leaked_threads, quantiles)."""
    from ouroboros_tpu import simharness as sim
    from ouroboros_tpu.network.snocket import SimSnocket
    from ouroboros_tpu.observe import export
    from ouroboros_tpu.observe.scrape import (
        PeriodicEmitter, ScrapeServer, scrape,
    )

    emitted = []

    async def main():
        sn = SimSnocket()
        srv = await ScrapeServer(sn, "metrics").start()
        em = await PeriodicEmitter(1.0, emitted.append).start()
        text = await scrape(sn, "metrics")
        await sim.sleep(2.5)
        await srv.stop()
        await em.stop()
        return text

    text, trace = sim.run_trace(main())
    leaked = len(sim.leaked_threads(trace))
    try:
        parsed = export.parse_prometheus_text(text)
        q = export.prom_histogram_quantiles(
            parsed, "ouro_pipeline_submit_drain_secs")
        ok = (parsed.get("ouro_pipeline_submit_drain_secs_count", 0) > 0
              and 0 < q["p50"] <= q["p95"] <= q["p99"]
              and len(emitted) >= 2)
    except Exception as e:
        log(f"scrape smoke failed to parse: {e!r}")
        ok, q = False, {}
    return ok, leaked, q


def _smoke_net_disabled():
    """Disabled-observation probe for the mux hot path (ISSUE 14): with
    metrics OFF, pumping SDUs through a mux pair in sim performs ZERO
    gated registry writes and ZERO label formats (netmetrics counts its
    own formatting on an `always` counter, so the assertion holds even
    while the registry flag is down), and the per-peer accounting object
    is never even built."""
    from ouroboros_tpu import simharness as sim
    from ouroboros_tpu.network.mux import Mux, bearer_pair
    from ouroboros_tpu.observe import metrics as _om
    from ouroboros_tpu.observe import netmetrics as _net

    reg = _om.REGISTRY
    was = reg.enabled
    reg.disable()
    try:
        writes0 = reg.data_writes
        formats0 = _net.LABEL_FORMATS.value
        io_built = []

        async def main():
            ba, bb = bearer_pair(sdu_size=1024)
            ma, mb = Mux(ba, "smoke-net-a"), Mux(bb, "smoke-net-b")
            ma.start()
            mb.start()
            cha = ma.channel(2, 0)
            chb = mb.channel(2, 1)
            await cha.send(b"x" * 4096)
            got = b""
            while len(got) < 4096:
                got += await chb.recv()
            io_built.append((ma._io, mb._io))
            ma.stop()
            mb.stop()
            return len(got)

        n = sim.run(main(), seed=1)
        writes = reg.data_writes - writes0
        formats = _net.LABEL_FORMATS.value - formats0
        built = any(io is not None for pair in io_built for io in pair)
        return {"ok": bool(writes == 0 and formats == 0
                           and not built and n == 4096),
                "sdu_bytes": int(n),
                "disabled_net_writes": int(writes),
                "disabled_label_formats": int(formats),
                "mux_io_built": bool(built)}
    finally:
        reg.enabled = was


def _smoke_perfgate():
    """Run the trajectory gate over the committed BENCH_r*.json rounds —
    tier-1 fails the moment a regressed round is recorded (the prose
    trajectory in ROADMAP becomes an enforced gate).  Since ISSUE 11 the
    MULTICHIP rounds ride along: once a green sharded-replay round is
    recorded, a later red mesh round (rc!=0, unattributed compile, or
    parity lost) fails tier-1 too — rounds predating the sharded replay
    are tolerated as skipped.  Since ISSUE 14 the serve section is gated
    the same way: once a recorded round carries one, the latest must
    hold the 5x-vs-unbatched + p95-inside-deadline bar."""
    from tools.perfgate import check_multichip, check_serve, \
        check_trajectory
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not paths:
        return True, {"checks": [], "note": "no recorded rounds"}
    verdict = check_trajectory(paths)
    sv = check_serve(paths)
    verdict["serve"] = sv
    verdict["ok"] = verdict["ok"] and sv["ok"]
    mc_paths = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    if mc_paths:
        mc = check_multichip(mc_paths)
        verdict["multichip"] = mc
        verdict["ok"] = verdict["ok"] and mc["ok"]
    if not verdict["ok"]:
        log(f"perfgate FAILED: {json.dumps(verdict['checks'])} "
            f"{json.dumps(sv['checks'])} "
            f"{json.dumps(verdict.get('multichip', {}).get('checks', []))}")
    return verdict["ok"], verdict


def _smoke_sharded_replay(rules, blocks_l, mesh_n: int = 2,
                          window: int = 4):
    """Sharded pipelined replay smoke (ISSUE 11): over `mesh_n` forced
    host-platform devices, the threaded sharded ReplayResult must be
    byte-identical to the synchronous single-device driver on a valid,
    a tampered, and a truncated chain, with zero leaked producer
    threads.

    Gated on the COST, not just the API surface: a sharded composite
    costs minutes of XLA:CPU compile (257s/182s measured at exactly
    these smoke shapes) — past the whole tier-1 budget — regardless of
    whether shard_map is experimental (this container's jax 0.4.x) or
    graduated, so the probe skips on host-platform devices and on
    experimental-only shard_map, recording why.  Real accelerators run
    it per smoke; `OURO_SMOKE_MESH=1` forces it anywhere (e.g. a
    CPU-only CI lane with a long budget);
    `__graft_entry__.dryrun_multichip` covers the mesh path per round
    in this container."""
    import jax
    forced = os.environ.get("OURO_SMOKE_MESH") == "1"
    if not forced and not hasattr(jax, "shard_map"):
        return {"ok": True,
                "skipped": "experimental-only shard_map: sharded "
                           "composite compile (~3-4 min XLA:CPU) "
                           "exceeds the tier-1 budget; covered by "
                           "dryrun_multichip + slow sharded parity "
                           "tests"}
    if not forced and jax.devices()[0].platform not in ("tpu", "gpu"):
        return {"ok": True,
                "skipped": "host-platform devices: the sharded "
                           "composite's multi-minute XLA:CPU compile "
                           "exceeds the tier-1 budget on any jax "
                           "version (OURO_SMOKE_MESH=1 forces the "
                           "probe); covered by dryrun_multichip"}
    if len(jax.devices()) < mesh_n:
        return {"ok": False, "skipped": None,
                "error": f"need {mesh_n} devices, have "
                         f"{len(jax.devices())} (XLA_FLAGS host-device "
                         f"forcing must precede jax init)"}
    from ouroboros_tpu.consensus.batch import replay_blocks_pipelined
    from ouroboros_tpu.consensus.headers import ProtocolBlock
    from ouroboros_tpu.crypto.backend import GLOBAL_BETA_CACHE
    from ouroboros_tpu.eras.shelley import KES_FIELD
    from ouroboros_tpu.parallel import ShardedJaxBackend, make_mesh

    def tamper(blks, ix):
        blk = blks[ix]
        sig = bytearray(blk.header.get(KES_FIELD))
        sig[3] ^= 1
        out = list(blks)
        out[ix] = ProtocolBlock(
            blk.header.with_fields(**{KES_FIELD: bytes(sig)}), blk.body)
        return out

    sb = ShardedJaxBackend(make_mesh(mesh_n), min_bucket=16)
    cpu = _cpu_backend()
    variants = [list(blocks_l), tamper(blocks_l, 5),
                list(blocks_l[:3]) + list(blocks_l[4:])]
    ok = True
    details = []
    for blks in variants:
        GLOBAL_BETA_CACHE.clear()
        sync = replay_blocks_pipelined(rules, blks, rules.initial_state(),
                                       backend=cpu, window=window)
        GLOBAL_BETA_CACHE.clear()
        shard = replay_blocks_pipelined(rules, blks,
                                        rules.initial_state(),
                                        backend=sb, window=window)
        same = (shard.n_valid == sync.n_valid
                and (shard.error is None) == (sync.error is None)
                and ((shard.final_state is None)
                     == (sync.final_state is None))
                and (sync.final_state is None
                     or (shard.final_state.ledger.state_hash()
                         == sync.final_state.ledger.state_hash())))
        ok = ok and same
        details.append({"n_valid": [sync.n_valid, shard.n_valid],
                        "match": bool(same)})
    leaked = _smoke_producer_leak()
    return {"ok": bool(ok and leaked == 0), "skipped": None,
            "devices": mesh_n, "variants": details,
            "producer_threads_leaked": int(leaked),
            "padding": sb.padding_stats()}


def _clear_beta_cache():
    from ouroboros_tpu.crypto.backend import GLOBAL_BETA_CACHE
    GLOBAL_BETA_CACHE.clear()


def _smoke_stream(chain_dir, jb, cpu_hash):
    """Streaming-engine smoke (ISSUE 15): replay the smoke chain FROM
    DISK through storage/stream.py — prefetch thread + pipelined verify
    + DiskPolicy snapshot — then reopen with resume and restore the tip
    checkpoint.  Composite-shape discipline (tier1-budget memory): the
    KES outcome cache is re-colded first so the engine's window takes
    the SAME cold ('win', ne, nv, nb, nk) shape the parity replay
    already compiled — zero fresh XLA:CPU compiles; the window size (8)
    matches for the same reason.

    Gates: state-hash parity vs the CPU baseline, >=1 chunk streamed,
    >=1 crash-consistent snapshot written, the resumed reopen replays
    ZERO blocks to the SAME hash, and neither the prefetcher nor the
    producer leaks a thread."""
    from ouroboros_tpu.crypto.precompute import GLOBAL_PRECOMPUTE_CACHE
    from ouroboros_tpu.storage import (
        DiskPolicy, StreamConfig, StreamingReplayEngine,
    )
    from ouroboros_tpu.storage.stream import prefetcher_threads_alive

    fs, db, rules, decode = load_stream_ctx(chain_dir)
    cfg = StreamConfig(window=8, read_ahead=2,
                       policy=DiskPolicy(num_snapshots=2,
                                         snapshot_interval_slots=4),
                       resume=False)
    GLOBAL_PRECOMPUTE_CACHE._kes.clear()
    _clear_beta_cache()
    res = StreamingReplayEngine(fs, db, rules, decode, backend=jb,
                                config=cfg).replay()
    hash_ok = (res.all_valid
               and res.final_state.ledger.state_hash() == cpu_hash)
    # resume: restores the tip snapshot, streams nothing, same hash —
    # the restart-in-seconds contract, on the real backend, for free
    _clear_beta_cache()
    res2 = StreamingReplayEngine(
        fs, db, rules, decode, backend=jb,
        config=StreamConfig(window=8, read_ahead=2, policy=cfg.policy,
                            resume=True)).replay()
    resume_ok = (res2.all_valid and res2.n_valid == 0
                 and res2.stats["resumed_from_slot"] is not None
                 and res2.final_state.ledger.state_hash() == cpu_hash)
    leaked = prefetcher_threads_alive() + _smoke_producer_leak()
    ok = (hash_ok and resume_ok and res.stats["chunks_read"] >= 1
          and res.stats["snapshots_written"] >= 1 and leaked == 0)
    return {"ok": bool(ok), "state_hash_parity": bool(hash_ok),
            "resume_parity": bool(resume_ok),
            "resumed_from_slot": res2.stats["resumed_from_slot"],
            "restore_secs": res2.stats["restore_secs"],
            "threads_leaked": int(leaked),
            "stats": res.stats}


# ---------------------------------------------------------------------------
# --serve: the adaptive micro-batching verification service under seeded
# bursty arrival traces, in deterministic sim time (ISSUE 12)
# ---------------------------------------------------------------------------

# modeled serving costs used when no break-even calibration file exists
# for a real device (this container has none): ~libsodium-class 1 ms per
# CPU-reference proof vs a device batch costing a fixed ~2 ms dispatch +
# 20 µs per lane — the cost SHAPE every accelerator shares; the absolute
# numbers only scale the virtual clock.  With these, break-even is n*=3.
SERVE_MODEL_DEFAULTS = {"cpu_secs_per_req": 1e-3,
                        "device_setup_secs": 2e-3,
                        "device_secs_per_req": 2e-5}


def _serve_population():
    """A small pool of (request, expected-verdict) pairs covering every
    primitive, valid and corrupted — verdicts computed ONCE by the
    pure-Python oracle; the sim samples from the pool so a long trace
    costs no per-arrival EC math."""
    import hashlib

    from ouroboros_tpu.crypto import ed25519_ref, kes, vrf_ref
    from ouroboros_tpu.crypto.backend import (
        CpuRefBackend, Ed25519Req, KesReq, VrfReq,
    )
    sk = hashlib.sha256(b"serve-ed").digest()
    vk = ed25519_ref.public_key(sk)
    vsk = hashlib.sha256(b"serve-vrf").digest()
    vvk = vrf_ref.public_key(vsk)
    ksk = kes.KesSignKey(4, hashlib.sha256(b"serve-kes").digest())
    kvk = ksk.verification_key
    good_kes = ksk.sign(b"kmsg")
    reqs = [Ed25519Req(vk, b"m%d" % i, ed25519_ref.sign(sk, b"m%d" % i))
            for i in range(4)]
    reqs.append(Ed25519Req(vk, b"bad", ed25519_ref.sign(sk, b"good")))
    reqs += [VrfReq(vvk, b"a%d" % i, vrf_ref.prove(vsk, b"a%d" % i))
             for i in range(3)]
    reqs.append(VrfReq(vvk, b"bad-alpha", vrf_ref.prove(vsk, b"a0")))
    reqs += [KesReq(4, kvk, 0, b"kmsg", good_kes.to_bytes()),
             KesReq(4, kvk, 1, b"kmsg", good_kes.to_bytes()),   # bad
             KesReq(4, kvk, 0, b"kmsg", b"\x00" * 7)]           # bad
    oracle = CpuRefBackend()
    want = {}
    want.update(zip(reqs[:5], oracle.verify_ed25519_batch(reqs[:5])))
    want.update(zip(reqs[5:9], oracle.verify_vrf_batch(reqs[5:9])))
    want.update(zip(reqs[9:], oracle.verify_kes_batch(reqs[9:])))
    return [(r, bool(want[r])) for r in reqs], want


def _serve_trace(seed, phases, population):
    """Seeded bursty arrival trace: per phase (label, duration_secs,
    rate_per_sec), Poisson arrivals (exponential gaps) each carrying a
    request sampled from the population.  Returns [(t, req, want)] —
    the SAME trace drives the service sim and the unbatched baseline."""
    import random
    rng = random.Random(seed)
    out = []
    t = 0.0
    for _label, duration, rate in phases:
        end = t + duration
        while True:
            t += rng.expovariate(rate)
            if t >= end:
                t = end
                break
            req, want = population[rng.randrange(len(population))]
            out.append((t, req, want))
    return out


def _serve_unbatched_baseline(trace, cpu_secs_per_req):
    """The per-request CPU baseline on the same trace: one sequential
    CPU verifier (an M/D/1 queue), each request costing
    `cpu_secs_per_req`.  Exact discrete-event fold — no sim needed.
    Returns (makespan_secs, latencies)."""
    free_at = 0.0
    lat = []
    for t, _req, _want in trace:
        start = max(t, free_at)
        free_at = start + cpu_secs_per_req
        lat.append(free_at - t)
    return (free_at if trace else 0.0), lat


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return round(sorted_vals[i], 6)


def _run_serve_trace(trace, model, deadline, cfg_kw, break_even):
    """One seeded trace through the VerifyService in deterministic sim
    time.  Returns (stats dict, latencies, parity_ok, leaked)."""
    from ouroboros_tpu import simharness as sim
    from ouroboros_tpu.crypto.backend import CpuRefBackend
    from ouroboros_tpu.crypto.batching import (
        ModeledBackend, PrecheckedBackend, ServiceConfig, VerifyService,
    )
    arrivals = trace["arrivals"]
    lookup = PrecheckedBackend(CpuRefBackend(), dict(trace["want"]))
    device = ModeledBackend(model["device_setup_secs"],
                            model["device_secs_per_req"], inner=lookup,
                            name="modeled-device")
    cpu = ModeledBackend(0.0, model["cpu_secs_per_req"], inner=lookup,
                         name="modeled-cpu")
    results = []

    async def client(req, want):
        t0 = sim.now()
        ok = await svc.verify(req, deadline=deadline)
        results.append((sim.now() - t0, bool(ok) == want))

    svc = None

    async def main():
        nonlocal svc
        cfg = ServiceConfig(
            initial_latency=model["device_setup_secs"], **cfg_kw)
        svc = await VerifyService(device, cpu_ref=cpu, config=cfg,
                                  break_even=break_even).start()
        tasks = []
        for t, req, want in arrivals:
            gap = t - sim.now()
            if gap > 0:
                await sim.sleep(gap)
            tasks.append(sim.spawn(client(req, want),
                                   label=f"serve-client-{len(tasks)}"))
        for task in tasks:
            await task.wait()
        makespan = sim.now()
        await svc.stop()
        return makespan

    makespan, sim_trace = sim.run_trace(main())
    leaked = len(sim.leaked_threads(sim_trace))
    lat = sorted(l for l, _ in results)
    parity = all(ok for _, ok in results) and len(results) == len(arrivals)
    return {"makespan_secs": round(makespan, 6),
            "service": dict(svc.stats),
            "batch_size_hist": {str(k): svc.batch_sizes[k]
                                for k in sorted(svc.batch_sizes)}}, \
        lat, parity, leaked


def _serve_break_even(model, bucket=256):
    """BreakEvenTable derived from the latency model — NEVER from a
    persisted calibration file: the serve legs are a deterministic
    tier-1 gate, so routing (n*) and the modeled costs it was derived
    from must come from the same place.  Real-device calibration
    (`calibrate_break_even`, persisted beside the autotune choices) is
    for production services, where the same backend that was measured
    does the serving."""
    from ouroboros_tpu.crypto.batching import BreakEvenTable
    dev_batch = (model["device_setup_secs"]
                 + model["device_secs_per_req"] * bucket)
    cpu_one = model["cpu_secs_per_req"]
    # device cost is setup-dominated at coalescer sizes: break even where
    # n sequential CPU verifies outrun one device dispatch of n
    n_star = 1
    while (model["device_setup_secs"]
           + model["device_secs_per_req"] * n_star) >= cpu_one * n_star \
            and n_star < bucket:
        n_star += 1
    entries = {p: {"n_star": int(n_star),
                   "cpu_secs_per_req": cpu_one,
                   "device_secs_batch": round(dev_batch, 9),
                   "bucket": bucket}
               for p in ("ed25519", "vrf", "kes")}
    return BreakEvenTable(entries, "modeled-device"), True


def serve_bench(seed: int = 7, scale: float = 1.0,
                deadline: float = 0.05) -> dict:
    """The ``serve`` section: the coalescing service vs the unbatched
    per-request CPU baseline on seeded bursty sim traces.

    Three legs, all deterministic virtual time at a fixed seed:

    * **saturated** — Poisson warm phase + burst phases well past the
      single-CPU rate: the service must sustain >= 5x the unbatched
      baseline with p95 request latency inside the deadline;
    * **light_load** — arrival gaps far above the coalescing window:
      every flush is below break-even, so ZERO device dispatches (the
      whole trace rides the CPU fallback);
    * **backpressure** — a near-simultaneous burst against a tiny
      admission queue: submitters block (the back-pressure contract),
      nothing is lost, every verdict still lands.

    `scale` shrinks the trace for the tier-1 smoke (sub-minute);
    verdict parity vs the pure-Python oracle is asserted on EVERY leg.
    """
    population, want = _serve_population()
    model = dict(SERVE_MODEL_DEFAULTS)
    break_even, modeled = _serve_break_even(model)
    n_star = break_even.n_star("ed25519")

    def run(phases, cfg_kw):
        arrivals = _serve_trace(seed, phases, population)
        stats, lat, parity, leaked = _run_serve_trace(
            {"arrivals": arrivals, "want": want}, model, deadline,
            cfg_kw, break_even)
        return arrivals, stats, lat, parity, leaked

    out = {"seed": seed, "deadline_secs": deadline,
           "modeled_costs": modeled, "model": model,
           "break_even": break_even.snapshot()}

    # -- saturated: every phase's arrival rate sits well past the single-
    # CPU service rate (1/cpu_secs_per_req = 1000/s on the default
    # model), so the measured makespan ratio is the CAPACITY gap, not an
    # arrival-rate artifact — a cooldown below the CPU rate would let
    # the baseline catch up while the service idles
    phases = [("warm", 0.4 * scale, 5000.0),
              ("burst", 0.2 * scale, 10000.0)]
    arrivals, stats, lat, parity, leaked = run(
        phases, {"max_batch": 256, "max_queue": 2048})
    cpu_makespan, cpu_lat = _serve_unbatched_baseline(
        arrivals, model["cpu_secs_per_req"])
    cpu_lat.sort()
    n = len(arrivals)
    svc_stats = stats["service"]
    misses = svc_stats["deadline_misses"]
    out["saturated"] = {
        "phases": [[p, round(d, 3), r] for p, d, r in phases],
        "requests": n,
        "makespan_secs": stats["makespan_secs"],
        "proofs_per_sec": round(n / stats["makespan_secs"], 1),
        "cpu_unbatched_makespan_secs": round(cpu_makespan, 6),
        "cpu_unbatched_proofs_per_sec": round(n / cpu_makespan, 1),
        "vs_unbatched_cpu": round(cpu_makespan / stats["makespan_secs"],
                                  2),
        "latency": {"p50": _pct(lat, 0.50), "p95": _pct(lat, 0.95),
                    "p99": _pct(lat, 0.99)},
        "cpu_unbatched_latency": {"p50": _pct(cpu_lat, 0.50),
                                  "p95": _pct(cpu_lat, 0.95),
                                  "p99": _pct(cpu_lat, 0.99)},
        "p95_within_deadline": _pct(lat, 0.95) <= deadline,
        "deadline_misses": misses,
        "deadline_miss_frac": round(misses / n, 4) if n else 0.0,
        "service": svc_stats,
        "batch_size_hist": stats["batch_size_hist"],
        "parity": parity,
        "leaked_threads": leaked,
    }

    # -- light load: gaps far above the coalescing window -------------------
    phases = [("idle", max(8.0 * scale, 2.0), 2.0)]
    arrivals, stats, lat, parity, leaked = run(
        phases, {"max_batch": 256, "max_queue": 2048})
    svc_stats = stats["service"]
    out["light_load"] = {
        "requests": len(arrivals),
        "break_even_n": n_star,
        "device_batches": svc_stats["device_batches"],
        "fallback_requests": svc_stats["fallback_requests"],
        "latency_p95": _pct(lat, 0.95),
        "parity": parity,
        "leaked_threads": leaked,
    }

    # -- back-pressure: burst >> tiny admission queue -----------------------
    phases = [("slam", 0.01, 20000.0)]
    arrivals, stats, lat, parity, leaked = run(
        phases, {"max_batch": 64, "max_queue": 32})
    svc_stats = stats["service"]
    out["backpressure"] = {
        "requests": len(arrivals),
        "max_queue": 32,
        "backpressure_waits": svc_stats["backpressure_waits"],
        "completed": svc_stats["submitted"],
        "parity": parity,
        "leaked_threads": leaked,
    }
    out["ok"] = bool(
        out["saturated"]["parity"] and out["light_load"]["parity"]
        and out["backpressure"]["parity"]
        and out["saturated"]["vs_unbatched_cpu"] >= 5.0
        and out["saturated"]["p95_within_deadline"]
        and out["light_load"]["device_batches"] == 0
        and out["saturated"]["leaked_threads"] == 0
        and out["light_load"]["leaked_threads"] == 0
        and out["backpressure"]["leaked_threads"] == 0)
    return out


def _smoke_serve():
    """Sub-minute serve probe for --smoke/tier-1: the scaled-down
    serve_bench — parity on every leg, >=5x over the unbatched CPU
    baseline at saturation, p95 inside the deadline, zero device
    dispatches under light load, zero leaked sim threads."""
    res = serve_bench(seed=7, scale=0.5)
    return res


def _stream_leg(chain_dir, jb, cpu_hash, n_proofs):
    """The ``stream`` section of a bench round (ISSUE 15): ONE replay of
    the same chain FROM DISK through the streaming engine — read-ahead
    prefetch + pipelined verify + periodic snapshots — on the
    already-warm backend (every window shape was pinned by the main
    replays), then a resumed reopen restoring the tip checkpoint.  The
    disk_hidden_frac it reports is the engine's whole point: the
    fraction of disk+decode seconds that ran while a window was in
    flight on device."""
    from ouroboros_tpu.storage import (
        DiskPolicy, StreamConfig, StreamingReplayEngine,
    )
    fs, db, rules, decode = load_stream_ctx(chain_dir)
    cfg = StreamConfig(window=WINDOW, read_ahead=4,
                       policy=DiskPolicy(num_snapshots=2,
                                         snapshot_interval_slots=max(
                                             1, EPOCH_LEN)),
                       resume=False)
    _clear_beta_cache()
    res = StreamingReplayEngine(fs, db, rules, decode, backend=jb,
                                config=cfg).replay()
    if not res.all_valid:
        raise SystemExit(f"stream leg failed at block {res.n_valid}: "
                         f"{res.error}")
    parity = res.final_state.ledger.state_hash() == cpu_hash
    _clear_beta_cache()
    res2 = StreamingReplayEngine(
        fs, db, rules, decode, backend=jb,
        config=StreamConfig(window=WINDOW, read_ahead=4,
                            policy=cfg.policy, resume=True)).replay()
    out = dict(res.stats)
    out["state_hash_parity"] = bool(parity)
    out["proofs_per_sec"] = round(n_proofs / res.stats["replay_secs"], 1)
    out["restart"] = {
        "restore_secs": res2.stats["restore_secs"],
        "blocks_replayed": res2.n_valid,
        "state_hash_parity": bool(
            res2.all_valid and res2.final_state is not None
            and res2.final_state.ledger.state_hash() == cpu_hash),
    }
    if not parity:
        raise SystemExit("stream leg state hash parity violated")
    return out


def _mesh_leg(rules, blocks, cpu_hash, cpu_secs, tpu_secs, n_proofs,
              mesh_n: int):
    """The sharded pipelined replay leg of the bench (ISSUE 11): the
    SAME chain and window size through replay_blocks_pipelined over a
    ShardedJaxBackend — threaded producer/consumer, per-shard packed
    windows, fold verdicts — with the identical measurement discipline
    (cold-beta warmup x2, fenced timed reps, state-hash parity per rep).
    Returns the ``sharded`` dict for the output JSON."""
    import jax

    from ouroboros_tpu.crypto.backend import GLOBAL_BETA_CACHE
    from ouroboros_tpu.parallel import (
        ShardedJaxBackend, log_compile_time, make_mesh,
    )
    if len(jax.devices()) < mesh_n:
        raise SystemExit(
            f"--mesh {mesh_n}: only {len(jax.devices())} devices "
            f"visible (host-platform forcing happens before jax init; "
            f"re-run as a fresh process)")
    sb = TimingBackend(ShardedJaxBackend(make_mesh(mesh_n)))
    # warmup replay 1: compiles BOTH sharded window shapes (beta-carrying
    # and final beta-free) + the fold programs, fills the key cache —
    # attributed so a multi-minute XLA:CPU compile is named, not mystery
    with log_compile_time(f"mesh={mesh_n} sharded replay warmup"):
        GLOBAL_BETA_CACHE.clear()
        replay(rules, blocks, sb, WINDOW)
    # warmup replay 2: warm key cache, steady-state shapes
    GLOBAL_BETA_CACHE.clear()
    replay(rules, blocks, sb, WINDOW)
    pad0 = sb.padding_stats()
    times, dev_times, disp_times = [], [], []
    for _ in range(REPS):
        GLOBAL_BETA_CACHE.clear()
        _device_fence()
        sb.device_secs = sb.dispatch_secs = 0.0
        secs, mesh_hash, _ = replay(rules, blocks, sb, WINDOW)
        assert mesh_hash == cpu_hash, \
            "sharded replay state hash parity violated"
        times.append(secs)
        dev_times.append(sb.device_secs)
        disp_times.append(sb.dispatch_secs)
    med, spread = check_spread("sharded replay", times)
    return {
        "devices": mesh_n,
        "proofs_per_sec": round(n_proofs / med, 1),
        "vs_baseline": round(cpu_secs / med, 3),
        "vs_single_device": round(tpu_secs / med, 3),
        "replay_secs": {"median": round(med, 3),
                        "min": round(min(times), 3),
                        "max": round(max(times), 3)},
        "spread": round(spread, 3),
        # same attribution discipline as the single-device breakdown:
        # consumer-thread blocking drains vs producer-thread pack+submit
        "device_wait_secs": round(statistics.median(dev_times), 3),
        "dispatch_secs": round(statistics.median(disp_times), 3),
        "state_hash_parity": True,
        "padding": sb.padding_stats(since=pad0),   # timed reps only
    }


def main(mesh_n: int = None):
    from ouroboros_tpu.crypto.jax_backend import JaxBackend

    tmp = tempfile.mkdtemp(prefix="bench-shelley-")
    try:
        chain = synth_chain(tmp)
        rules, blocks = load(chain)

        from ouroboros_tpu.crypto.backend import GLOBAL_BETA_CACHE

        # CPU baseline: sequential C++ (libsodium-class) replay.  Median of
        # CPU_REPS — host-local and compute-bound, so far less noisy than
        # the device path, but still repeated for honesty.
        cpu = _cpu_backend()
        cpu_times = []
        cpu_hash = n_proofs = None
        for _ in range(CPU_REPS):
            GLOBAL_BETA_CACHE.clear()   # cold cache for every timed replay
            secs, cpu_hash, n_proofs = replay(rules, blocks, cpu, WINDOW)
            cpu_times.append(secs)
        cpu_secs, cpu_spread = check_spread("cpu replay", cpu_times)
        log(f"cpu [{cpu.name}] replay: median {cpu_secs:.2f}s over "
            f"{CPU_REPS} reps (spread {100 * cpu_spread:.0f}%; "
            f"{n_proofs / cpu_secs:.0f} proofs/s, "
            f"{len(blocks) / cpu_secs:.0f} blocks/s)")

        # TPU path: warm-up replay from a cold cache (compiles, autotunes
        # AND precomputes exactly the shapes/keys the timed runs use),
        # then REPS timed replays, each from a cold beta cache but a WARM
        # per-key precomputation cache (the steady state: zero per-key
        # device work, only the ladders)
        from ouroboros_tpu.crypto import autotune
        from ouroboros_tpu.crypto.precompute import GLOBAL_PRECOMPUTE_CACHE
        jb = TimingBackend(JaxBackend())
        GLOBAL_BETA_CACHE.clear()
        replay(rules, blocks, jb, WINDOW)       # cold warmup: compiles,
        #                                         fills the key cache,
        #                                         pins cold window shapes
        log(f"precompute after warmup: {GLOBAL_PRECOMPUTE_CACHE.stats()}")
        # SECOND warmup from the now-warm key cache: warm windows carry
        # zero KES hash jobs, i.e. a DIFFERENT composite shape
        # ('win', ne, nv, nb, 0) than the cold pass — it must be pinned
        # (and compiled) before the tuners freeze, or the first timed
        # rep would be the one paying for it
        GLOBAL_BETA_CACHE.clear()
        replay(rules, blocks, jb, WINDOW)
        warm_fills = GLOBAL_PRECOMPUTE_CACHE.device_fills
        tpu_times, dev_times, disp_times = [], [], []
        rep_phases: list = []
        rep_overlaps: list = []
        tpu_hash = None
        # per-rep phase attribution (ISSUE 7): spans on for the timed
        # reps only — each rep yields sync/compile/dispatch/device/
        # host-seq totals, so a spread warning names the phase that
        # moved instead of leaving a bare 45% number
        from ouroboros_tpu import observe
        observe.spans.RECORDER.enable()
        autotune.freeze_all()   # any mid-bench retune now raises
        try:
            for _ in range(REPS):
                jb.device_secs = jb.dispatch_secs = 0.0
                GLOBAL_BETA_CACHE.clear()
                with observe.span("rep.fence", cat="sync", fence=True):
                    pass        # drain in-flight dispatches pre-rep
                # discard pre-rep spans (the fence above ran OUTSIDE the
                # timed rep — attributing it would make phases sum past
                # rep_secs and under-report `other`)
                observe.spans.RECORDER.drain()
                secs, tpu_hash, _ = replay(rules, blocks, jb, WINDOW)
                tpu_times.append(secs)
                dev_times.append(jb.device_secs)
                disp_times.append(jb.dispatch_secs)
                roots = observe.spans.RECORDER.drain()
                rep_phases.append(_rep_phase_totals(observe, roots, secs))
                rep_overlaps.append(_rep_overlap(observe, roots))
        except autotune.FrozenAutotunerError as e:
            raise SystemExit(
                f"mid-bench retune attempt inside a timed replay rep "
                f"({e}); the two warmup replays failed to pin every "
                f"window shape — numbers from this run are not "
                f"trustworthy") from e
        finally:
            autotune.thaw_all()
            observe.spans.RECORDER.disable()
        assert tpu_hash == cpu_hash, "state hash parity violated"
        warm_extra_fills = (GLOBAL_PRECOMPUTE_CACHE.device_fills
                            - warm_fills)
        assert warm_extra_fills == 0, (
            f"cache-warm replay dispatched {warm_extra_fills} per-key "
            f"fill kernels; the precomputation cache is leaking work "
            f"into the steady state")
        tpu_secs, tpu_spread = check_spread("tpu replay", tpu_times)
        dev_secs = statistics.median(dev_times)
        disp_secs = statistics.median(disp_times)
        overlap = _overlap_summary(rep_overlaps)
        log(f"tpu replay: median {tpu_secs:.2f}s over {REPS} reps "
            f"(spread {100 * tpu_spread:.0f}%; "
            f"{n_proofs / tpu_secs:.0f} proofs/s, "
            f"{len(blocks) / tpu_secs:.0f} blocks/s); "
            f"device-wait {dev_secs:.2f}s / dispatch {disp_secs:.2f}s "
            f"(producer thread)")
        if overlap:
            log(f"overlap: host-seq {overlap['host_seq_secs_median']:.2f}s "
                f"of which {overlap['host_hidden_secs_median']:.2f}s "
                f"({100 * overlap['hidden_frac_median']:.0f}%) hidden "
                f"under in-flight device windows; producer stalled "
                f"{overlap['producer_stall_secs_median']:.2f}s on the "
                f"permit gate")
        variance = _phase_variance(rep_phases)
        if variance:
            dom = variance["dominant_phase"]
            log(f"variance: largest cross-rep spread in phase '{dom}' "
                f"({variance['dominant_spread_secs']:.2f}s min->max; "
                f"per-phase "
                f"{ {p: v['spread_secs'] for p, v in variance['per_phase'].items()} })")

        prim = bench_primitives(JaxBackend())
        log(f"primitives: {prim}")
        prim_vs_prev = compare_previous(prim)
        vrf_attr = vrf_attribution(prim)
        if vrf_attr:
            log(f"vrf primitive below best recorded round: {vrf_attr}")

        # streaming-engine leg: the same chain replayed FROM DISK with
        # read-ahead + snapshots + a resumed restart (warm shapes only)
        stream = _stream_leg(chain, jb, cpu_hash, n_proofs)
        log(f"stream: {stream['disk_secs']}s disk+decode, "
            f"{100 * stream['disk_hidden_frac']:.0f}% hidden under "
            f"device; {stream['snapshots_written']} snapshots, restart "
            f"restored in {stream['restart']['restore_secs']}s")

        sharded = None
        if mesh_n:
            sharded = _mesh_leg(rules, blocks, cpu_hash, cpu_secs,
                                tpu_secs, n_proofs, mesh_n)
            log(f"sharded (mesh={mesh_n}): {sharded}")

        # belt-and-braces: a frozen write RAISES at the store site (the
        # except above / _timed_reps), so reaching here with a nonzero
        # count means some future code swallowed the error — still fail
        if autotune.frozen_write_count() != 0:
            raise SystemExit(
                "kernel choices were written inside a timed region — "
                "the warmup phase failed to pin every shape")
        rate = n_proofs / tpu_secs
        print(json.dumps({
            "metric": "shelley_replay_proofs_per_sec",
            "value": round(rate, 1),
            "unit": "proofs/s",
            "vs_baseline": round(tpu_secs and (cpu_secs / tpu_secs), 3),
            "blocks_per_sec": round(len(blocks) / tpu_secs, 1),
            "cpu_baseline_proofs_per_sec": round(n_proofs / cpu_secs, 1),
            "state_hash_parity": True,
            "reps": REPS,
            "spread": round(tpu_spread, 3),
            "replay_secs": {"median": round(tpu_secs, 3),
                            "min": round(min(tpu_times), 3),
                            "max": round(max(tpu_times), 3)},
            "cpu_replay_secs": {"median": round(cpu_secs, 3),
                                "spread": round(cpu_spread, 3)},
            "breakdown": {
                # device_wait = caller-thread blocking drains; dispatch =
                # producer-thread packing+submit (overlapped with the
                # waits, so the two may legitimately sum past wall time)
                "device_wait_secs": round(dev_secs, 3),
                "dispatch_secs": round(disp_secs, 3),
                "host_secs": round(tpu_secs - dev_secs, 3)},
            "overlap": overlap,
            "phases": rep_phases,
            "variance": variance,
            "metrics": observe.metrics.registry().snapshot(),
            "kernel_choices": {
                "@".join(str(p) for p in k): ("pallas" if v else "xla")
                for k, v in jb._inner.kernel_choices.items()},
            "precompute": GLOBAL_PRECOMPUTE_CACHE.stats(),
            "primitives": prim,
            "primitives_vs_previous": prim_vs_prev,
            "stream": stream,
            **({"vrf_attribution": vrf_attr} if vrf_attr else {}),
            **({"sharded": sharded} if sharded else {}),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny parity-only replay (1 rep, no timing "
                         "assertions); the tier-1 replay-path gate")
    ap.add_argument("--retune", action="store_true",
                    help="invalidate the persisted kernel choices and "
                         "re-measure pallas-vs-XLA from scratch")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="also run the sharded pipelined replay over an "
                         "N-device mesh (forced host-platform devices "
                         "off-TPU) and report sharded proofs/s beside "
                         "the single-device number")
    ap.add_argument("--serve", action="store_true",
                    help="the adaptive micro-batching verification "
                         "service under seeded bursty arrival traces "
                         "in deterministic sim time: p50/p95/p99 "
                         "request latency and proofs/s vs the "
                         "unbatched per-request CPU baseline "
                         "(crypto/batching.py, ROADMAP item 3)")
    ap.add_argument("--serve-seed", type=int, default=7,
                    help="arrival-trace seed for --serve (default 7)")
    args = ap.parse_args()
    if args.retune:
        # tuner_for() reads this when the first backend is constructed
        os.environ["OURO_RETUNE"] = "1"
    if args.mesh or args.smoke:
        # mesh legs need multiple XLA devices; forcing host-platform
        # devices only works BEFORE jax initialises, which is why this
        # sits in __main__ (module level stays jax-free) and why the
        # flag is a no-op on real TPU platforms (it only multiplies the
        # HOST platform's device count)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            n = max(args.mesh or 0, 2)
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    if args.serve:
        res = serve_bench(seed=args.serve_seed)
        print(json.dumps({
            "metric": "verify_service_serve",
            "value": res["saturated"]["proofs_per_sec"],
            "unit": "proofs/s",
            "vs_unbatched_cpu": res["saturated"]["vs_unbatched_cpu"],
            "serve": res}))
        if not res["ok"]:
            raise SystemExit("bench --serve gate failure (see 'serve' "
                             "section)")
    elif args.smoke:
        smoke()
    else:
        main(mesh_n=args.mesh)
