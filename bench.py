#!/usr/bin/env python
"""Benchmark: batched Ed25519 verification throughput vs single-core CPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The workload is BASELINE.md config #4's primitive (Ed25519 witness verify,
the dominant cost of block-body validation) run as one device batch, against
the OpenSSL (libsodium-class) single-core sequential loop the reference's
execution model corresponds to.  vs_baseline > 1 means the TPU path beats
sequential CPU verification.
"""
import json
import sys
import time

sys.path.insert(0, ".")


def main():
    import hashlib

    import jax
    import jax.numpy as jnp

    from ouroboros_tpu.crypto import ed25519_ref
    from ouroboros_tpu.crypto import ed25519_jax as EJ

    N = 8192
    sk = hashlib.sha256(b"bench-key").digest()
    vk = ed25519_ref.public_key(sk)
    msgs = [b"header-%06d" % i for i in range(N)]
    # sign with OpenSSL (fast) — same key, distinct messages
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    key = Ed25519PrivateKey.from_private_bytes(sk)
    sigs = [key.sign(m) for m in msgs]

    # --- CPU baseline: sequential OpenSSL verify, single core --------------
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )
    pub = Ed25519PublicKey.from_public_bytes(vk)
    ncpu = 2048
    t0 = time.perf_counter()
    for i in range(ncpu):
        pub.verify(sigs[i], msgs[i])
    cpu_rate = ncpu / (time.perf_counter() - t0)

    # --- TPU batched path (fused full-device kernel, software-pipelined) ----
    # Host prep of batch i+1 overlaps device execution of batch i via JAX
    # async dispatch; steady-state throughput = max(host, device) rate.
    import numpy as np

    vks = [vk] * N
    reps = 4
    batches = []
    for r in range(reps):
        bm = [b"hdr-%d-%06d" % (r, i) for i in range(N)]
        batches.append((bm, [key.sign(m) for m in bm]))
    # warm-up / compile
    EJ.batch_verify(vks, batches[0][0], batches[0][1])
    t0 = time.perf_counter()
    pending = []
    for bm, bs in batches:
        arrays, parse_ok = EJ.prepare_bytes_batch(vks, bm, bs)
        ok_dev = EJ.verify_kernel_full_submit(arrays)
        pending.append((ok_dev, parse_ok))
    results = []
    for ok_dev, parse_ok in pending:
        ok = np.asarray(ok_dev)
        results.append(bool(ok.all()) and bool(parse_ok.all()))
    dt = (time.perf_counter() - t0) / reps
    assert all(results), "bench batch failed verification"
    rate = N / dt

    print(json.dumps({
        "metric": "ed25519_batch_verify_throughput_e2e",
        "value": round(rate, 1),
        "unit": "verifies/s",
        "vs_baseline": round(rate / cpu_rate, 3),
    }))


if __name__ == "__main__":
    main()
