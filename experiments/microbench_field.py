#!/usr/bin/env python
"""Microbenchmark the crypto hot path on the real chip: device-only kernel
times vs host-prep times, plus per-field-op costs inside a pallas kernel.

Run on the TPU machine:  python experiments/microbench_field.py [--ops]
"""
import argparse
import os
import statistics
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(tempfile.gettempdir(), "jax-ouro-cache"))

import numpy as np  # noqa: E402


def timed(fn, reps=7, warm=2):
    for _ in range(warm):
        fn()
    vals = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        vals.append(time.perf_counter() - t0)
    vals.sort()
    return vals[len(vals) // 2], vals[0], vals[-1]


def report(name, med, lo, hi, per=None):
    extra = f"  ({per})" if per else ""
    print(f"{name:42s} med {med*1e3:8.1f}ms  min {lo*1e3:8.1f}  "
          f"max {hi*1e3:8.1f}{extra}", flush=True)


def bench_e2e():
    import hashlib

    import jax.numpy as jnp

    from ouroboros_tpu.crypto import ed25519_jax as EJ
    from ouroboros_tpu.crypto import ed25519_ref, kes, vrf_jax, vrf_ref
    from ouroboros_tpu.crypto import pallas_kernels as PK
    from ouroboros_tpu.crypto.backend import KesReq

    n = 4096
    sk = hashlib.sha256(b"bench-ed").digest()
    vk = ed25519_ref.public_key(sk)
    msgs = [b"m%06d" % i for i in range(n)]
    sigs = [ed25519_ref.sign(sk, m) for m in msgs]
    vks = [vk] * n
    print("fixtures: ed ready", flush=True)

    # host prep
    med, lo, hi = timed(lambda: EJ.prepare_bytes_batch(vks, msgs, sigs))
    report(f"ed prep_bytes_batch n={n}", med, lo, hi)

    arrays, _ok = EJ.prepare_bytes_batch(vks, msgs, sigs)
    yA, signA, yR, signR, s_bits, k_bits = arrays
    dev = [jnp.asarray(a) for a in
           (yA, signA.reshape(1, -1), yR, signR.reshape(1, -1),
            s_bits, k_bits)]

    def run_pallas():
        return np.asarray(PK._ed25519_verify_jit(*dev, n))

    med, lo, hi = timed(run_pallas)
    report(f"ed pallas device n={n}", med, lo, hi,
           per=f"{n/med:.0f}/s")

    # transfer cost: host->device of the same arrays
    def xfer():
        a = [jnp.asarray(x) for x in
             (yA, signA.reshape(1, -1), yR, signR.reshape(1, -1),
              s_bits, k_bits)]
        a[0].block_until_ready()
    med, lo, hi = timed(xfer)
    report(f"ed h2d transfer n={n}", med, lo, hi)

    # VRF (proof generation is pure-Python EC and slow: cache to disk)
    nv = 2048
    vsk = hashlib.sha256(b"bench-vrf").digest()
    vvk = vrf_ref.public_key(vsk)
    alphas = [b"a%d" % i for i in range(nv)]
    cache = os.path.join(tempfile.gettempdir(), f"ouro-vrf-proofs-{nv}.bin")
    if os.path.exists(cache):
        raw = open(cache, "rb").read()
        proofs = [raw[i * 80:(i + 1) * 80] for i in range(nv)]
    else:
        proofs = [vrf_ref.prove(vsk, a) for a in alphas]
        open(cache, "wb").write(b"".join(proofs))
    vvks = [vvk] * nv
    print("fixtures: vrf ready", flush=True)

    med, lo, hi = timed(lambda: vrf_jax._prepare(vvks, alphas, proofs))
    report(f"vrf _prepare n={nv}", med, lo, hi)

    args, parse_ok, gamma_ok, s_ok, pf_arr = vrf_jax._prepare(
        vvks, alphas, proofs)

    def run_vrf():
        return np.asarray(PK.vrf_verify_pallas(*args))
    med, lo, hi = timed(run_vrf)
    report(f"vrf pallas device n={nv}", med, lo, hi, per=f"{nv/med:.0f}/s")

    rows = np.asarray(PK.vrf_verify_pallas(*args))
    med, lo, hi = timed(lambda: vrf_jax._finish(rows, parse_ok, gamma_ok,
                                                s_ok, pf_arr, nv))
    report(f"vrf _finish n={nv}", med, lo, hi)

    # betas
    med, lo, hi = timed(lambda: vrf_jax._prepare_betas(proofs))
    report(f"beta _prepare n={nv}", med, lo, hi)
    (yG, signG), decode_ok = vrf_jax._prepare_betas(proofs)

    def run_beta():
        return np.asarray(PK.gamma8_pallas(yG, signG))
    med, lo, hi = timed(run_beta)
    report(f"beta pallas device n={nv}", med, lo, hi, per=f"{nv/med:.0f}/s")

    rows_b = np.asarray(PK.gamma8_pallas(yG, signG))
    med, lo, hi = timed(lambda: vrf_jax._finish_betas(rows_b, decode_ok, nv))
    report(f"beta _finish n={nv}", med, lo, hi)

    # KES host hash path
    nk = 4096
    ksk = kes.KesSignKey(6, hashlib.sha256(b"bench-kes").digest())
    kreqs = [KesReq(6, ksk.verification_key, 0, b"m%d" % i,
                    ksk.sign(b"m%d" % i).to_bytes()) for i in range(nk)]
    from ouroboros_tpu.crypto.backend import CryptoBackend
    cb = CryptoBackend()
    med, lo, hi = timed(lambda: cb.split_mixed(kreqs))
    report(f"kes split_mixed (host hash path) n={nk}", med, lo, hi)


def bench_ops():
    """Per-op costs inside a pallas kernel: chains of K ops, difference two
    K values to cancel fixed overhead."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ouroboros_tpu.crypto import ed25519_jax as EJ
    from ouroboros_tpu.crypto import field_jax as F

    TILE = 512
    GRID = 8
    N = TILE * GRID
    rng = np.random.default_rng(0)
    a_np = rng.integers(0, 8191, size=(F.NLIMBS, N), dtype=np.int32)
    b_np = rng.integers(0, 8191, size=(F.NLIMBS, N), dtype=np.int32)

    def make_chain(op_name, k):
        def kernel(a_ref, b_ref, o_ref):
            a = a_ref[:]
            b = b_ref[:]

            def body(i, a):
                if op_name == "mul":
                    return F.mul(a, b)
                if op_name == "sqr":
                    return F.mul(a, a)
                if op_name == "add":
                    return F.add(a, b)
                if op_name == "carry":
                    return F.carry_round(a)
                raise ValueError(op_name)
            o_ref[:] = lax.fori_loop(0, k, body, a)

        lane = lambda i: (0, i)
        spec = pl.BlockSpec((F.NLIMBS, TILE), lane, memory_space=pltpu.VMEM)
        with F.mul_impl("columns"):
            f = pl.pallas_call(
                kernel, grid=(GRID,), in_specs=[spec, spec], out_specs=spec,
                out_shape=jax.ShapeDtypeStruct((F.NLIMBS, N), jnp.int32))
        return jax.jit(f)

    def make_pt_chain(kind, k):
        """Chain of point ops: kind in dbl | addc (add with fixed point)."""
        def kernel(x_ref, y_ref, z_ref, t_ref, o_ref):
            P = (x_ref[:], y_ref[:], z_ref[:], t_ref[:])
            Q = P

            def body(i, Q):
                if kind == "dbl":
                    return EJ.pt_double(Q)
                return EJ.pt_add(Q, P, TILE)
            Q = lax.fori_loop(0, k, body, Q)
            o_ref[:] = Q[0] + Q[1] + Q[2] + Q[3]

        lane = lambda i: (0, i)
        spec = pl.BlockSpec((F.NLIMBS, TILE), lane, memory_space=pltpu.VMEM)
        with F.mul_impl("columns"):
            f = pl.pallas_call(
                kernel, grid=(GRID,), in_specs=[spec] * 4, out_specs=spec,
                out_shape=jax.ShapeDtypeStruct((F.NLIMBS, N), jnp.int32))
        return jax.jit(f)

    a = jnp.asarray(a_np)
    b = jnp.asarray(b_np)
    for op in ("mul", "sqr", "add", "carry"):
        k1, k2 = 64, 192
        f1, f2 = make_chain(op, k1), make_chain(op, k2)
        m1, _, _ = timed(lambda: np.asarray(f1(a, b)))
        m2, _, _ = timed(lambda: np.asarray(f2(a, b)))
        per = (m2 - m1) / (k2 - k1)
        print(f"field {op:6s}: {per*1e6:8.1f} us per batched op "
              f"(chain {k1}: {m1*1e3:.1f}ms, {k2}: {m2*1e3:.1f}ms)",
              flush=True)

    for kind in ("dbl", "addc"):
        k1, k2 = 32, 96
        f1, f2 = make_pt_chain(kind, k1), make_pt_chain(kind, k2)
        m1, _, _ = timed(lambda: np.asarray(f1(a, b, a, b)))
        m2, _, _ = timed(lambda: np.asarray(f2(a, b, a, b)))
        per = (m2 - m1) / (k2 - k1)
        print(f"point {kind:5s}: {per*1e6:8.1f} us per batched op "
              f"(chain {k1}: {m1*1e3:.1f}ms, {k2}: {m2*1e3:.1f}ms)",
              flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", action="store_true")
    ap.add_argument("--e2e", action="store_true")
    args = ap.parse_args()
    if not (args.ops or args.e2e):
        args.e2e = True
    if args.e2e:
        bench_e2e()
    if args.ops:
        bench_ops()
